"""Async communication ops: the post/wait split, as schedulable vertices.

Parity target: reference ``include/tenzing/mpi/ops_mpi.hpp`` (Isend / Irecv /
Ialltoallv / Wait / OwningWaitall / MultiWait, :17-146) and the SpMV batch comm
ops (``ops_spmv.cuh:217-304`` PostRecv/WaitRecv/PostSend/WaitSend).  The split
between *posting* a transfer and *waiting* for it IS the overlap opportunity
the search exists to exploit (SURVEY.md §7.0) — collapsing an exchange into one
synchronous op (round 1) removed the schedule freedom the solver is supposed to
explore.

TPU-native semantics.  The reference's Isend/Irecv are *host-posted* ops: the
network DMA proceeds asynchronously off-stream, and ``Wait`` (a CpuOp) blocks
the host chain (EventSynchronizer's CPU case table, event_synchronizer.hpp).
The analog here:

* a **start op** contributes the transfer to the traced program: its *inputs*
  are tied to the host chain at the post point (a transfer cannot begin before
  its source is produced and the host program reaches the post), but its
  *completion* is NOT joined into any chain — the in-flight value simply sits
  in the buffer dict, and XLA lowers it as an async pair (copy-start/copy-done
  for host transfers, collective-permute-start/done for ICI permutes) whose
  done is placed as late as data dependencies allow;
* an **AwaitTransfer** joins the in-flight value's completion into the host
  chain (reference ``Wait``): every op scheduled after it — on any lane —
  observes the transfer as finished; ops scheduled between the start and the
  await overlap the DMA.  ``MultiAwait`` waits a set (reference MultiWait).

Transfers available:

* :class:`HostSpillStart` / :class:`HostFetchStart` — device->host-pinned and
  host->device copies (the single-chip async DMA; PCIe on real hardware).  The
  TPU analog of ``cudaMemcpyAsync`` staging, and the measured substrate of the
  lane-overlap proof (runtime/executor.py docstring: 20.8 ms serialized vs
  14.0 ms overlapped on v5e).
* :class:`PermuteStart` — ``lax.ppermute`` over a mesh axis (ICI neighbor
  exchange; reference Isend+Irecv pair to a neighbor rank).  XLA lowers it to
  collective-permute-start/done; the await placement decides how much compute
  hides the ICI hop.

These are plain named graph vertices: serdes re-anchors them by name
(core/serdes.py), and they need no lane-assignment decision (host-posted, like
the reference's CpuOp comm ops) — the searched freedom is their *position* in
the order, exactly the reference's post/wait placement freedom.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence as Seq

from tenzing_tpu.core.operation import CpuOp, register_kind


def _to_memory_kind(x, kind: str):
    import jax

    dev = jax.devices()[0]
    return jax.device_put(x, jax.sharding.SingleDeviceSharding(dev, memory_kind=kind))


class CommStart(CpuOp):
    """Base: a host-posted async transfer (reference Isend/Irecv shape).

    Subclasses implement ``apply`` (the transfer's dataflow) and declare
    ``DST_SPACE`` ("host" or "device") — the executor tracks which buffers are
    host-resident because host-space tensors admit only pure copies (no
    tie arithmetic; measured TPU toolchain limitation).  Tracing ties the
    *device-side* end of the transfer to the host chain at the post point
    (source for spills/permutes, destination for fetches) but does NOT join
    completion into any chain — that is AwaitTransfer's job.
    """

    DST_SPACE = "device"

    def __init__(self, name: str, src: str, dst: str):
        super().__init__(name)
        self._src = src
        self._dst = dst

    def src(self) -> str:
        return self._src

    def dst(self) -> str:
        return self._dst

    def reads(self) -> List[str]:
        return [self._src]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs: Dict[str, Any], ctx) -> Dict[str, Any]:
        raise NotImplementedError

    def trace(self, tc) -> None:
        view = dict(tc.bufs)
        for name in self.reads():
            # host-space reads skip the tie inside tie_named; their post
            # ordering then rests on the destination-side tie below
            view[name] = tc.tie_named(name, view[name], tc._host_tok)
        out = self.apply(view, tc)
        for name, val in out.items():
            if name not in tc.bufs:
                raise KeyError(
                    f"comm op {self.desc()!r} writes undeclared buffer {name!r}"
                )
            if self.DST_SPACE == "host":
                tc.host_space.add(name)
            else:
                tc.host_space.discard(name)
                if self._src in tc.host_space:
                    # fetch from host: the source tie was skipped, so anchor
                    # the post point on the device result instead
                    val = tc._tie(val, tc._host_tok)
            tc.bufs[name] = val
        # deliberately NO chain advance: the transfer is in flight

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(), "src": self._src, "dst": self._dst}


@register_kind("host_spill_start")
class HostSpillStart(CommStart):
    """Post an async device->host copy of ``src`` into host buffer ``dst``."""

    DST_SPACE = "host"

    def apply(self, bufs, ctx):
        return {self._dst: _to_memory_kind(bufs[self._src], "pinned_host")}


@register_kind("host_fetch_start")
class HostFetchStart(CommStart):
    """Post an async host->device copy of ``src`` into device buffer ``dst``."""

    def apply(self, bufs, ctx):
        return {self._dst: _to_memory_kind(bufs[self._src], "device")}


@register_kind("permute_start")
class PermuteStart(CommStart):
    """Post a neighbor shift of ``src`` over mesh axis ``axis`` into ``dst``
    (ICI hop; XLA lowers to collective-permute-start/done)."""

    def __init__(self, name: str, src: str, dst: str, axis: str, shift: int = 1):
        super().__init__(name, src, dst)
        self._axis = axis
        self._shift = shift

    def apply(self, bufs, ctx):
        import jax

        n = jax.lax.axis_size(self._axis)
        s = self._shift % n
        perm = [(i, (i + s) % n) for i in range(n)]
        return {self._dst: jax.lax.ppermute(bufs[self._src], self._axis, perm)}

    def to_json(self) -> Dict[str, Any]:
        j = super().to_json()
        j.update(axis=self._axis, shift=self._shift)
        return j


@register_kind("all_to_all_start")
class AllToAllStart(CommStart):
    """Post a width-padded all-to-all over mesh axis ``axis`` — the reference
    ``Ialltoallv`` (ops_mpi.hpp:82-119), with raggedness handled by padding
    each pairwise segment to the common width (there is no ragged all-to-all
    on ICI).  ``src``/``dst`` are (batch, n, w)-per-shard buffers whose
    ``split_axis`` indexes the peer shard: out[:, q, :] is what shard q sent
    here."""

    def __init__(self, name: str, src: str, dst: str, axis: str,
                 split_axis: int = 1):
        super().__init__(name, src, dst)
        self._axis = axis
        self._split = split_axis

    def apply(self, bufs, ctx):
        import jax

        return {
            self._dst: jax.lax.all_to_all(
                bufs[self._src], self._axis, self._split, self._split
            )
        }

    def to_json(self) -> Dict[str, Any]:
        j = super().to_json()
        j.update(axis=self._axis, split_axis=self._split)
        return j


@register_kind("psum_start")
class PsumStart(CommStart):
    """Post an all-reduce (sum) of ``src`` over mesh axis ``axis`` into
    ``dst`` — the collective analog of the reference's nonblocking collective
    (Ialltoallv, ops_mpi.hpp:82-119) for the tensor-parallel pattern: XLA
    lowers it to all-reduce-start/done, and the await placement decides how
    much compute hides the reduction."""

    def __init__(self, name: str, src: str, dst: str, axis: str):
        super().__init__(name, src, dst)
        self._axis = axis

    def apply(self, bufs, ctx):
        import jax

        return {self._dst: jax.lax.psum(bufs[self._src], self._axis)}

    def to_json(self) -> Dict[str, Any]:
        j = super().to_json()
        j.update(axis=self._axis)
        return j


def _settle_inflight(tc, name: str) -> None:
    """If ``name`` has an explicit in-flight completion handle (split-kernel
    RDMA, ops/rdma.py), run its wait kernel now: the buffer value becomes the
    *completed* destination and downstream consumers (and the host-chain join)
    depend on the semaphore wait, not merely on the post."""
    pending = getattr(tc, "inflight", {}).pop(name, None)
    if pending is not None:
        tc.bufs[name] = pending(tc.bufs[name])


@register_kind("await_transfer")
class AwaitTransfer(CpuOp):
    """Wait for an in-flight buffer: joins its completion into the host chain
    (reference Wait, ops_mpi.hpp:121-131).  Ops ordered after this observe the
    transfer as done; ops between the start and this op overlap the DMA."""

    def __init__(self, name: str, buf: str):
        super().__init__(name)
        self._buf = buf

    def buf(self) -> str:
        return self._buf

    def reads(self) -> List[str]:
        return [self._buf]

    def trace(self, tc) -> None:
        from tenzing_tpu.runtime.executor import _clean, _scalarize

        if self._buf in tc.host_space:
            # a spilled (host-resident) buffer exposes no device-readable
            # completion handle; with SSA buffers a spill needs no wait for
            # source reuse anyway — await the round-trip's fetch result instead
            return
        _settle_inflight(tc, self._buf)
        tc._host_tok = tc._join(tc._host_tok, _clean(_scalarize(tc.bufs[self._buf])))

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(), "buf": self._buf}


@register_kind("multi_await")
class MultiAwait(CpuOp):
    """Wait for a set of in-flight buffers (reference MultiWait/OwningWaitall,
    ops_mpi.hpp:133-146): one schedulable op for the wait-all discipline."""

    def __init__(self, name: str, bufs: Seq[str]):
        super().__init__(name)
        self._bufs = list(bufs)

    def bufs(self) -> List[str]:
        return list(self._bufs)

    def reads(self) -> List[str]:
        return list(self._bufs)

    def trace(self, tc) -> None:
        from tenzing_tpu.runtime.executor import _clean, _scalarize

        for b in self._bufs:
            if b not in tc.host_space:
                _settle_inflight(tc, b)
        toks = [
            _clean(_scalarize(tc.bufs[b])) for b in self._bufs if b not in tc.host_space
        ]
        tc._host_tok = tc._join(tc._host_tok, *toks)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(), "bufs": list(self._bufs)}
