"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import jax

from tenzing_tpu.ops.pallas_compat import typeof


def out_struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying-across-mesh
    (vma) annotation — required for pallas_call under shard_map.  ``typeof``
    is the compat shim's: on jax without ``jax.typeof`` it degrades to an
    eval_shape struct with no vma (matching the vma-less shard_map there)."""
    vma = frozenset()
    for a in like:
        vma = vma | getattr(typeof(a), "vma", frozenset())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without vma
        return jax.ShapeDtypeStruct(shape, dtype)
