"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import jax


def out_struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying-across-mesh
    (vma) annotation — required for pallas_call under shard_map."""
    vma = frozenset()
    for a in like:
        vma = vma | getattr(jax.typeof(a), "vma", frozenset())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without vma
        return jax.ShapeDtypeStruct(shape, dtype)
