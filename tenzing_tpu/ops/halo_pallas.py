"""Pallas pack/unpack kernels for the halo faces — the kernel menu.

Parity target: the reference ships TWO CUDA kernel families for halo
pack/unpack, selected by storage order (``pack_kernel_qxyz`` warp-per-gridpoint
vs ``pack_kernel_xyzq`` thread-per-gridpoint, ops_halo_exchange.cu:519-573 and
the mirror unpack kernels :611-699, launch-config selection in Pack::run /
Unpack::run) — a per-workload implementation choice the search explores.

TPU-native menu: the XLA path (``models/halo.Pack``/``Unpack``) lowers the face
slice to XLA's fusion machinery; this module is the alternative — an explicit
**window-DMA kernel**: per (q, face-row) grid step the tile-aligned BOUNDING
WINDOW of the face cut (``_tile_window``) is DMA'd between HBM and VMEM with
``pltpu.make_async_copy`` and the ragged face cut is extracted (pack) or
merged (unpack read-modify-write, input/output-aliased: guaranteed in place)
in registers.  Mosaic requires HBM DMA slices tile-aligned (probed on v5e:
"Slice shape along dimension 3 must be aligned to tiling (128)"), so the
window is the aligned superset of the cut — a few extra aligned bytes for
aligned DMA, vs the XLA path's fused narrow copy whose in-place lowering
depends on XLA's liveness analysis.  Which wins per face shape (x-faces are
lane-contiguous, z-faces are 3-element strided in the lane dim) is exactly the
storage-order question the reference's two kernel families answer — so it is
exposed as a ChoiceOp and searched (SpMV's kernel menu precedent,
models/spmv.py SpMVImplChoice).

MEASURED (r5): the menu's value on the flagship is NOT kernel speed —
isolated and composed per-op costs differ 10-100x in both directions
(experiments/HALO_INCONTEXT.json vs MENU_INCUMBENT.json) because XLA
fuses/aliases across the whole program.  The load-bearing property is the
ALIASING GUARANTEE: at nq=3, 512^3 f32 the grid is 2.07 GB, a non-in-place
ghost-shell write costs a ~5 ms full-U copy, and the measured winners pick
exactly the aliased kernels per face (x .pallas, y .pallasf, z .pallasb —
experiments/MENU_INCUMBENT2.json: 2.94x vs the XLA-unpack recipe's 2.51x in
the same paired batch).

Off-TPU the kernels run in the Pallas interpreter (``interpret=True``), same
code path as the repo's other Pallas kernels.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import numpy as np

from tenzing_tpu.core.operation import ChoiceOp, OpBase
from tenzing_tpu.ops.pallas_compat import compiler_params as _compiler_params
from tenzing_tpu.models.halo import (
    HaloArgs,
    _face_slices,
    dir_name,
    sublane_tile,
)
from tenzing_tpu.models.halo_pipeline import (
    PackFlat,
    UnpackRecv,
    flatten_face,
    unflatten_face,
)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# The two-slot rotating-DMA kernels assume the grid executes strictly
# sequentially in linear order t = q*nb + b.  That is Pallas TPU's default
# today, but nothing else pins it — "arbitrary" makes the requirement
# explicit so a future parallel/megacore grid default can't silently race
# the rotating slots.
_SEQUENTIAL_GRID = _compiler_params(
    dimension_semantics=("arbitrary", "arbitrary")
)


def _two_slot_fetch(t, total, src_slice, slots, sems, emit):
    """The read-side two-slot choreography shared by both batched pack
    kernels: bootstrap the t==0 fetch, await this step's window, prefetch
    t+1 into the other slot, then run ``emit(window)`` on the landed rows.
    One definition so a fix lands in every user (ADVICE r4: the pattern was
    hand-duplicated across four kernels)."""

    def body(wa, sa, wb, sb):
        @pl.when(t == 0)
        def _():
            pltpu.make_async_copy(src_slice(t), wa, sa).start()

        pltpu.make_async_copy(src_slice(t), wa, sa).wait()

        @pl.when(t + 1 < total)
        def _():
            pltpu.make_async_copy(src_slice(t + 1), wb, sb).start()

        emit(wa)

    @pl.when(t % 2 == 0)
    def _():
        body(slots[0], sems[0], slots[1], sems[1])

    @pl.when(t % 2 == 1)
    def _():
        body(slots[1], sems[1], slots[0], sems[0])


def _two_slot_rmw(t, total, in_slice, out_slice, slots, in_sems, out_sems,
                  merge):
    """The read-modify-write two-slot choreography shared by both batched
    unpack kernels: fetch the step-t window (bootstrapped at t==0), drain the
    other slot's t-1 write-back before reusing it for the t+1 prefetch (the
    fetch reads disjoint rows, so the two DMAs fly together), run
    ``merge(window)``, post the write-back, and drain BOTH slots on the
    final step (the last write-back is never waited by a next prefetch)."""

    def body(wa, sai, sao, wb, sbi, sbo):
        @pl.when(t == 0)
        def _():
            pltpu.make_async_copy(in_slice(t), wa, sai).start()

        pltpu.make_async_copy(in_slice(t), wa, sai).wait()

        @pl.when(t + 1 < total)
        def _():
            @pl.when(t >= 1)
            def _():
                pltpu.make_async_copy(wb, out_slice(t - 1), sbo).wait()

            pltpu.make_async_copy(in_slice(t + 1), wb, sbi).start()

        merge(wa)
        pltpu.make_async_copy(wa, out_slice(t), sao).start()

        @pl.when(t == total - 1)
        def _():
            @pl.when(t >= 1)
            def _():
                pltpu.make_async_copy(wb, out_slice(t - 1), sbo).wait()

            pltpu.make_async_copy(wa, out_slice(t), sao).wait()

    @pl.when(t % 2 == 0)
    def _():
        body(slots[0], in_sems[0], out_sems[0], slots[1], in_sems[1],
             out_sems[1])

    @pl.when(t % 2 == 1)
    def _():
        body(slots[1], in_sems[1], out_sems[1], slots[0], in_sems[0],
             out_sems[0])


def _tile_window(y0: int, sy: int, z0: int, sz: int,
                 Y: int, Z: int, itemsize: int = 4) -> Tuple[int, int, int, int]:
    """(wy0, WH, wz0, WW): the tile-aligned bounding window of the face cut,
    clamped to the plane extents — Mosaic requires HBM DMA slices
    tile-aligned (probed on v5e; flagship grids are tile-padded by
    ``halo_pipeline._padded_shape`` so the clamp is inert there), and DMAing
    only the window instead of the full plane cuts the moved bytes up to 30x
    for sublane-thin faces (y-faces: one sublane-tile stripe) and 5x for
    lane-thin faces (z-faces: a (Y, 128) stripe).  The sublane tile scales
    with dtype width (8 for 4-byte, 16 for 2-byte, 32 for 1-byte)."""
    st = sublane_tile(itemsize)
    wy0 = (y0 // st) * st
    wy1 = min(-(-(y0 + sy) // st) * st, Y)
    wz0 = (z0 // 128) * 128
    wz1 = min(-(-(z0 + sz) // 128) * 128, Z)
    return wy0, wy1 - wy0, wz0, wz1 - wz0


def _batch_rows(sx: int, row_bytes: int, cap: int = 2_500_000) -> int:
    """Rows DMA'd per grid step: the largest divisor of ``sx`` whose window
    fits the per-slot VMEM budget (two slots + the block-pipelined face
    buffers must stay well under the ~16 MB core VMEM).  1 means the batched
    kernel degenerates to the per-row kernel."""
    best = 1
    for b in range(1, sx + 1):
        if sx % b == 0 and b * row_bytes <= cap:
            best = b
    return best


@functools.partial(
    jax.jit, static_argnames=("starts", "sizes", "interpret")
)
def pack_face_pallas(
    u: jax.Array, starts: Tuple[int, ...], sizes: Tuple[int, ...], interpret: bool = False
) -> jax.Array:
    """out[q, i, :, :] = u[q, x0+i, y0:y0+sy, z0:z0+sz]: aligned bounding
    -window DMA in, ragged face cut extracted in VMEM."""
    nq, sx, sy, sz = sizes
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)

    def kernel(u_ref, o_ref, win, sem):
        q = pl.program_id(0)
        i = pl.program_id(1)
        cp = pltpu.make_async_copy(
            u_ref.at[q, x0 + i, pl.ds(wy0, WH), pl.ds(wz0, WW)], win, sem
        )
        cp.start()
        cp.wait()
        o_ref[0, 0] = win[y0 - wy0 : y0 - wy0 + sy, z0 - wz0 : z0 - wz0 + sz]

    return pl.pallas_call(
        kernel,
        grid=(nq, sx),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, 1, sy, sz), lambda q, i: (q, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, sx, sy, sz), u.dtype),
        scratch_shapes=[pltpu.VMEM((WH, WW), u.dtype), pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )(u)


@functools.partial(jax.jit, static_argnames=("starts", "interpret"))
def unpack_face_pallas(
    u: jax.Array, face: jax.Array, starts: Tuple[int, ...], interpret: bool = False
) -> jax.Array:
    """u[q, x0+i, y0:y0+sy, z0:z0+sz] = face[q, i, :, :], in place (aliased —
    GUARANTEED, unlike a dynamic-update-slice whose in-place lowering depends
    on XLA's liveness analysis of the surrounding schedule): read-modify
    -write of each touched aligned bounding window through VMEM."""
    nq, sx, sy, sz = face.shape
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)

    def kernel(u_ref, f_ref, o_ref, win, sem):
        q = pl.program_id(0)
        i = pl.program_id(1)
        cp_in = pltpu.make_async_copy(
            u_ref.at[q, x0 + i, pl.ds(wy0, WH), pl.ds(wz0, WW)], win, sem
        )
        cp_in.start()
        cp_in.wait()
        win[y0 - wy0 : y0 - wy0 + sy, z0 - wz0 : z0 - wz0 + sz] = f_ref[0, 0]
        cp_out = pltpu.make_async_copy(
            win, o_ref.at[q, x0 + i, pl.ds(wy0, WH), pl.ds(wz0, WW)], sem
        )
        cp_out.start()
        cp_out.wait()

    return pl.pallas_call(
        kernel,
        grid=(nq, sx),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1, sy, sz), lambda q, i: (q, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        scratch_shapes=[pltpu.VMEM((WH, WW), u.dtype), pltpu.SemaphoreType.DMA],
        input_output_aliases={0: 0},
        interpret=interpret,
    )(u, face)


@functools.partial(
    jax.jit, static_argnames=("starts", "sizes", "interpret")
)
def pack_face_pallas_batched(
    u: jax.Array, starts: Tuple[int, ...], sizes: Tuple[int, ...],
    interpret: bool = False
) -> jax.Array:
    """Batched-row pack: one aligned window DMA moves ``BX`` face rows
    ((BX, WH, WW) per step instead of (WH, WW)), and the NEXT step's window
    DMA is prefetched into the other of two rotating VMEM slots while the
    current rows are extracted — MB-scale DMAs instead of the per-row
    kernel's 1536 serial ~20-266 KB transfers at the flagship config, which
    are DMA-latency-bound, not bandwidth-bound (measured: the per-row y-face
    kernels spend ~4 us/step on ~25 us of face bytes)."""
    nq, sx, sy, sz = sizes
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)
    BX = _batch_rows(sx, WH * WW * u.dtype.itemsize)
    nb = sx // BX
    total = nq * nb
    yl, zl = y0 - wy0, z0 - wz0

    def kernel(u_ref, o_ref, win0, win1, s0, s1):
        t = pl.program_id(0) * nb + pl.program_id(1)

        def u_slice(tt):
            qq = tt // nb
            bb = tt - qq * nb
            return u_ref.at[
                qq, pl.ds(x0 + bb * BX, BX), pl.ds(wy0, WH), pl.ds(wz0, WW)
            ]

        def emit(wa):
            o_ref[0] = wa[:, yl : yl + sy, zl : zl + sz]

        _two_slot_fetch(t, total, u_slice, (win0, win1), (s0, s1), emit)

    return pl.pallas_call(
        kernel,
        grid=(nq, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, BX, sy, sz), lambda q, b: (q, b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nq, sx, sy, sz), u.dtype),
        scratch_shapes=[
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_SEQUENTIAL_GRID,
        interpret=interpret,
    )(u)


@functools.partial(jax.jit, static_argnames=("starts", "interpret"))
def unpack_face_pallas_batched(
    u: jax.Array, face: jax.Array, starts: Tuple[int, ...],
    interpret: bool = False
) -> jax.Array:
    """Batched-row unpack with software-pipelined in/out DMAs: two rotating
    (BX, WH, WW) VMEM slots; at step t the slot-t window (started at t-1)
    is awaited, the face rows are merged, its write-back DMA is posted, and
    the t+1 window fetch is posted into the other slot — so the write-back
    of step t rides concurrently with the fetch of step t+1 (disjoint row
    ranges of the aliased grid).  In place like the per-row kernel
    (input/output-aliased)."""
    nq, sx, sy, sz = face.shape
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)
    BX = _batch_rows(sx, WH * WW * u.dtype.itemsize)
    nb = sx // BX
    total = nq * nb
    yl, zl = y0 - wy0, z0 - wz0

    def kernel(u_ref, f_ref, o_ref, win0, win1, s0i, s1i, s0o, s1o):
        t = pl.program_id(0) * nb + pl.program_id(1)

        def slice_of(ref):
            def at(tt):
                qq = tt // nb
                bb = tt - qq * nb
                return ref.at[
                    qq, pl.ds(x0 + bb * BX, BX), pl.ds(wy0, WH),
                    pl.ds(wz0, WW)
                ]

            return at

        def merge(wa):
            wa[:, yl : yl + sy, zl : zl + sz] = f_ref[0]

        _two_slot_rmw(t, total, slice_of(u_ref), slice_of(o_ref),
                      (win0, win1), (s0i, s1i), (s0o, s1o), merge)

    return pl.pallas_call(
        kernel,
        grid=(nq, nb),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, BX, sy, sz), lambda q, b: (q, b, 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        scratch_shapes=[
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={0: 0},
        compiler_params=_SEQUENTIAL_GRID,
        interpret=interpret,
    )(u, face)


@functools.partial(
    jax.jit, static_argnames=("starts", "sizes", "interpret")
)
def pack_face_flat_pallas(
    u: jax.Array, starts: Tuple[int, ...], sizes: Tuple[int, ...],
    interpret: bool = False
) -> jax.Array:
    """Batched-row pack emitting the dense (rows, 128) STAGING layout
    directly: the face rows are extracted from the aligned window in VMEM and
    relaid to the flat layout with an in-kernel reshape (vreg shuffles at
    VMEM bandwidth), so the separate XLA flatten pass — measured at
    ~10 ms/iter of chunked HBM relayout copies across the winner's schedule
    (experiments/profile_winner.py) — disappears, while the staging buffer
    stays dense (the 4D-staging A/B showed tile-padded staging pays 2.7x+
    DMA bytes).  Requires sz % 128 == 0 (the ``_flat_ok`` gate): that keeps
    every (BX, sy, sz) block row-aligned in the flat buffer AND the relayout
    a sublane merge Mosaic can lower — z-faces (sz = radius) fail the Mosaic
    relayout pass, probed on v5e.  The two-slot DMA choreography is the
    shared ``_two_slot_fetch`` — one definition for both pack kernels."""
    nq, sx, sy, sz = sizes
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    assert sz % 128 == 0, (sy, sz)  # _flat_ok gate
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)
    BX = _batch_rows(sx, WH * WW * u.dtype.itemsize)
    nb = sx // BX
    total = nq * nb
    br = (BX * sy * sz) // 128  # flat rows per block
    yl, zl = y0 - wy0, z0 - wz0

    def kernel(u_ref, o_ref, win0, win1, s0, s1):
        t = pl.program_id(0) * nb + pl.program_id(1)

        def u_slice(tt):
            qq = tt // nb
            bb = tt - qq * nb
            return u_ref.at[
                qq, pl.ds(x0 + bb * BX, BX), pl.ds(wy0, WH), pl.ds(wz0, WW)
            ]

        def emit(wa):
            o_ref[...] = wa[:, yl : yl + sy, zl : zl + sz].reshape(br, 128)

        _two_slot_fetch(t, total, u_slice, (win0, win1), (s0, s1), emit)

    return pl.pallas_call(
        kernel,
        grid=(nq, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((br, 128), lambda q, b: (q * nb + b, 0)),
        out_shape=jax.ShapeDtypeStruct((nq * sx * sy * sz // 128, 128),
                                       u.dtype),
        scratch_shapes=[
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_SEQUENTIAL_GRID,
        interpret=interpret,
    )(u)


@functools.partial(jax.jit, static_argnames=("starts", "sizes", "interpret"))
def unpack_face_flat_pallas(
    u: jax.Array, flat: jax.Array, starts: Tuple[int, ...],
    sizes: Tuple[int, ...], interpret: bool = False
) -> jax.Array:
    """Batched-row unpack consuming the dense (rows, 128) staging buffer
    directly (inverse of :func:`pack_face_flat_pallas`): each flat block is
    relaid to face rows in VMEM and merged into the aligned window, with the
    same two-slot fetch/write-back pipeline and final drain as the batched
    window kernel.  Aliased in place."""
    nq, sx, sy, sz = sizes
    _, x0, y0, z0 = starts
    _, _, Y, Z = u.shape
    assert sz % 128 == 0, (sy, sz)  # _flat_ok gate
    wy0, WH, wz0, WW = _tile_window(y0, sy, z0, sz, Y, Z, u.dtype.itemsize)
    BX = _batch_rows(sx, WH * WW * u.dtype.itemsize)
    nb = sx // BX
    total = nq * nb
    br = (BX * sy * sz) // 128
    yl, zl = y0 - wy0, z0 - wz0

    def kernel(u_ref, f_ref, o_ref, win0, win1, s0i, s1i, s0o, s1o):
        t = pl.program_id(0) * nb + pl.program_id(1)

        def slice_of(ref):
            def at(tt):
                qq = tt // nb
                bb = tt - qq * nb
                return ref.at[
                    qq, pl.ds(x0 + bb * BX, BX), pl.ds(wy0, WH),
                    pl.ds(wz0, WW)
                ]

            return at

        def merge(wa):
            wa[:, yl : yl + sy, zl : zl + sz] = f_ref[...].reshape(BX, sy, sz)

        _two_slot_rmw(t, total, slice_of(u_ref), slice_of(o_ref),
                      (win0, win1), (s0i, s1i), (s0o, s1o), merge)

    return pl.pallas_call(
        kernel,
        grid=(nq, nb),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((br, 128), lambda q, b: (q * nb + b, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        scratch_shapes=[
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.VMEM((BX, WH, WW), u.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={0: 0},
        compiler_params=_SEQUENTIAL_GRID,
        interpret=interpret,
    )(u, flat)


# -- ops + choice menu ------------------------------------------------------------


class PackPallas(PackFlat):
    """Pack via the plane-DMA kernel, then flatten to the (rows, 128) staging
    layout (menu alternative to the XLA slice).

    INDEX_TIE stays OFF: the Pallas grid needs static start indices, so this
    variant keeps the value-tied read (the executor's default)."""

    INDEX_TIE = False

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"pack_{dir_name(d)}.pallas"

    def apply(self, bufs, ctx):
        starts, sizes = _face_slices(self._args, self._d, "pack")
        out = pack_face_pallas(
            bufs["U"], tuple(starts), tuple(sizes), interpret=_interpret()
        )
        return {f"buf_{dir_name(self._d)}": flatten_face(out, sizes)}

    def uses_pallas(self) -> bool:
        return True


class PackXla(PackFlat):
    """The XLA-slice pack under a menu-distinct name."""

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"pack_{dir_name(d)}.xla"


def _face_bx(args: HaloArgs, d, which: str = "pack") -> int:
    """The batched kernels' rows-per-DMA for this face (1 means the batched
    variant degenerates to the per-row kernel and is left off the menu).
    ``which`` picks the window the kernel will actually DMA — the pack reads
    the interior edge, the unpack RMWs the ghost shell, and the two can span
    a different number of sublane tiles.  The itemsize comes from the grid
    dtype in ``args`` so the gate agrees with the BX the kernels compute from
    ``u.dtype.itemsize`` (a 2-byte grid halves the sublane tile)."""
    from tenzing_tpu.models.halo_pipeline import _padded_shape

    itemsize = args.itemsize()
    starts, sizes = _face_slices(args, d, "pack")
    if which == "unpack":
        starts, _ = _face_slices(args, d, "unpack")
    _, sx, sy, sz = sizes
    _, _, y0, z0 = starts
    _, _, Y, Z = _padded_shape(args.local_shape(), itemsize)
    _, WH, _, WW = _tile_window(y0, sy, z0, sz, Y, Z, itemsize)
    return _batch_rows(sx, WH * WW * itemsize)


class PackPallasB(PackFlat):
    """Pack via the batched-row prefetching window kernel."""

    INDEX_TIE = False

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"pack_{dir_name(d)}.pallasb"

    def apply(self, bufs, ctx):
        starts, sizes = _face_slices(self._args, self._d, "pack")
        out = pack_face_pallas_batched(
            bufs["U"], tuple(starts), tuple(sizes), interpret=_interpret()
        )
        return {f"buf_{dir_name(self._d)}": flatten_face(out, sizes)}

    def uses_pallas(self) -> bool:
        return True


class UnpackPallas(UnpackRecv):
    """Unpack via the aliased plane-DMA kernel."""

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"unpack_{dir_name(d)}.pallas"

    def apply(self, bufs, ctx):
        starts, _ = _face_slices(self._args, self._d, "unpack")
        _, sizes = _face_slices(self._args, self._d, "pack")
        face = unflatten_face(bufs[f"recv_{dir_name(self._d)}"], sizes)
        out = unpack_face_pallas(
            bufs["U"], face, tuple(starts), interpret=_interpret()
        )
        return {"U": out}

    def uses_pallas(self) -> bool:
        return True


def _flat_ok(args: HaloArgs, d) -> bool:
    """Whether the direct-flat kernels apply: the face's trailing dim must be
    lane-aligned (sz % 128 == 0) — that makes every block row-aligned in the
    (rows, 128) staging buffer AND keeps the in-kernel relayout a
    sublane-merge Mosaic can lower (probed on v5e: a 3-wide trailing dim —
    z-faces — fails in the Mosaic relayout pass)."""
    _, sizes = _face_slices(args, d, "pack")
    return sizes[3] % 128 == 0


class PackPallasF(PackFlat):
    """Pack via the direct-flat kernel: dense staging emitted straight from
    the grid window, relayout in VMEM (no separate XLA flatten pass)."""

    INDEX_TIE = False

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"pack_{dir_name(d)}.pallasf"

    def apply(self, bufs, ctx):
        starts, sizes = _face_slices(self._args, self._d, "pack")
        out = pack_face_flat_pallas(
            bufs["U"], tuple(starts), tuple(sizes), interpret=_interpret()
        )
        return {f"buf_{dir_name(self._d)}": out}

    def uses_pallas(self) -> bool:
        return True


class UnpackXla(UnpackRecv):
    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"unpack_{dir_name(d)}.xla"


class UnpackPallasF(UnpackRecv):
    """Unpack via the direct-flat kernel (consumes the dense staging buffer
    with no separate XLA unflatten pass; aliased in place)."""

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"unpack_{dir_name(d)}.pallasf"

    def apply(self, bufs, ctx):
        starts, _ = _face_slices(self._args, self._d, "unpack")
        _, sizes = _face_slices(self._args, self._d, "pack")
        out = unpack_face_flat_pallas(
            bufs["U"], bufs[f"recv_{dir_name(self._d)}"], tuple(starts),
            tuple(sizes), interpret=_interpret()
        )
        return {"U": out}

    def uses_pallas(self) -> bool:
        return True


class UnpackPallasB(UnpackRecv):
    """Unpack via the batched-row in/out-pipelined aliased window kernel."""

    def __init__(self, args: HaloArgs, d):
        super().__init__(args, d)
        self._name = f"unpack_{dir_name(d)}.pallasb"

    def apply(self, bufs, ctx):
        starts, _ = _face_slices(self._args, self._d, "unpack")
        _, sizes = _face_slices(self._args, self._d, "pack")
        face = unflatten_face(bufs[f"recv_{dir_name(self._d)}"], sizes)
        out = unpack_face_pallas_batched(
            bufs["U"], face, tuple(starts), interpret=_interpret()
        )
        return {"U": out}

    def uses_pallas(self) -> bool:
        return True


class PackChoice(ChoiceOp):
    """XLA slice vs Pallas DMA kernel for one direction's pack (the reference's
    storage-order kernel-family selection as a searched ChoiceOp)."""

    def __init__(self, args: HaloArgs, d):
        super().__init__(f"pack_{dir_name(d)}")
        self._args, self._d = args, tuple(d)

    def choices(self) -> List[OpBase]:
        menu: List[OpBase] = [
            PackXla(self._args, self._d), PackPallas(self._args, self._d)
        ]
        if _face_bx(self._args, self._d) > 1:
            menu.append(PackPallasB(self._args, self._d))
        if _flat_ok(self._args, self._d):
            menu.append(PackPallasF(self._args, self._d))
        return menu


class UnpackChoice(ChoiceOp):
    def __init__(self, args: HaloArgs, d):
        super().__init__(f"unpack_{dir_name(d)}")
        self._args, self._d = args, tuple(d)

    def choices(self) -> List[OpBase]:
        menu: List[OpBase] = [
            UnpackXla(self._args, self._d), UnpackPallas(self._args, self._d)
        ]
        if _face_bx(self._args, self._d, which="unpack") > 1:
            menu.append(UnpackPallasB(self._args, self._d))
        if _flat_ok(self._args, self._d):
            menu.append(UnpackPallasF(self._args, self._d))
        return menu
