"""Pallas remote-DMA comm ops: the direct Isend/Irecv/Wait analog.

Parity target: reference ``include/tenzing/mpi/ops_mpi.hpp:17-146`` — the
nonblocking Isend/Irecv post whose completion a separate ``Wait`` op observes.
SURVEY.md §7.0 names ``pltpu.make_async_remote_copy`` + semaphores as the
TPU-native realization: the post/wait split *is* the overlap opportunity the
search exists to exploit, and on TPU the DMA engines move the bytes while the
TensorCore keeps executing kernels.

Two ops, two dispatch regimes:

* :class:`RdmaCopyStart` — device->device copy through the chip's RDMA engine
  addressed to the device itself (the single-chip realization of a
  device-resident transfer; the "CUDA-aware MPI" analog of SURVEY §7.0's
  translation table — device buffers addressed by ICI DMA, no host staging —
  vs the host-staged round trip of ``HostSpillStart``/``HostFetchStart``,
  the non-GPU-aware staging analog).  On a real TPU the post and the wait are
  **separate Pallas kernels** passing DMA semaphores between them
  (semaphores-in-out_shape): the start kernel issues ``rdma.start()`` and
  returns immediately, the schedule runs whatever it placed between post and
  await on the TensorCore, and ``AwaitTransfer`` runs the wait kernel that
  blocks on the semaphores — exactly MPI_Isend/MPI_Wait.  Under the Pallas
  interpreter (CPU tests) semaphore outputs are unsupported, so the op
  degrades to one fused local-DMA copy kernel (on one chip the loopback
  remote copy is the same data movement) — numerically identical, the
  overlap being a hardware property anyway.

* :class:`RdmaShiftStart` — neighbor shift over a mesh axis, each shard
  DMA-writing its block into the next shard's output buffer
  (``make_async_remote_copy`` with MESH device ids) after a neighbor barrier
  (``get_barrier_semaphore``) — the per-neighbor computed-offset DMA that is
  the TPU analog of the reference's negotiated per-rank exchange
  (``row_part_spmv.cuh:259-423``).  A searchable ChoiceOp alternative to
  ``PermuteStart`` (XLA collective-permute) in the halo and irregular-SpMV
  menus.  On TPU the post and the wait are separate kernels
  (``rdma_shift_post`` barriers + ``rdma.start()`` and returns semaphores;
  ``rdma_shift_wait`` blocks on them from the AwaitTransfer), so the searched
  post/wait placement is physical overlap freedom exactly as for the loopback
  copy.  Under the interpreter the op degrades to the fused start+wait kernel
  (semaphore outputs unsupported — probed).  When the axis has size 1 the
  shift degenerates to the loopback copy (no barrier — Mosaic rejects
  ``collective_id`` when no custom barrier is used, probed on v5e).

Validated on hardware: the split start/wait loopback copy round-trips 64 MB
correctly on TPU v5e (allclose), and in interpret mode on an 8-device CPU mesh
the shift matches ``jnp.roll`` along 1-D and 3-D meshes (tests/test_rdma.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tenzing_tpu.core.operation import register_kind
from tenzing_tpu.ops.comm_ops import CommStart
from tenzing_tpu.ops.pallas_compat import compiler_params as _compiler_params


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mesh_ids(axes: Tuple[str, ...], axis: Optional[str], shift: int):
    """(device_id fwd, device_id bwd, device_id_type, axis size) for the
    shifted neighbor pair on the current mesh."""
    if axis is None or not axes:
        return 0, 0, pltpu.DeviceIdType.LOGICAL, 1
    n = jax.lax.axis_size(axis)
    me = {a: jax.lax.axis_index(a) for a in axes}
    fwd = dict(me)
    fwd[axis] = (me[axis] + shift) % n
    bwd = dict(me)
    bwd[axis] = (me[axis] - shift) % n
    fwd_id = tuple(fwd[a] for a in axes)
    bwd_id = tuple(bwd[a] for a in axes)
    return fwd_id, bwd_id, pltpu.DeviceIdType.MESH, n


def _shift_fused_kernel(axes, axis, shift, x_ref, y_ref, send_sem, recv_sem):
    fwd, bwd, id_type, n = _mesh_ids(axes, axis, shift)
    if n > 1:
        # both neighbors must have entered the kernel before either side's
        # buffers are written remotely (standard RDMA ring discipline)
        barrier = pltpu.get_barrier_semaphore()
        for nb in (fwd, bwd):
            pltpu.semaphore_signal(barrier, inc=1, device_id=nb, device_id_type=id_type)
        pltpu.semaphore_wait(barrier, 2)
    rdma = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=y_ref, send_sem=send_sem, recv_sem=recv_sem,
        device_id=fwd, device_id_type=id_type,
    )
    rdma.start()
    rdma.wait()


def rdma_shift_fused(
    x: jax.Array,
    axes: Tuple[str, ...],
    axis: Optional[str],
    shift: int,
    collective_id: int = 0,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused (start+wait) remote-DMA shift of ``x`` to the ``+shift`` neighbor
    along ``axis``; the output holds the block received from ``-shift``."""
    if interpret is None:
        interpret = _interpret()
    kern = functools.partial(_shift_fused_kernel, tuple(axes), axis, shift)
    needs_barrier = axis is not None and axes and jax.lax.axis_size(axis) > 1
    params = (
        _compiler_params(collective_id=collective_id, has_side_effects=True)
        if needs_barrier
        else _compiler_params(has_side_effects=True)
    )
    return pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=params,
        interpret=pltpu.InterpretParams() if interpret else False,
        name="rdma_shift_fused",
    )(x)


def _loop_local_kernel(x_ref, y_ref, sem):
    cp = pltpu.make_async_copy(x_ref, y_ref, sem)
    cp.start()
    cp.wait()


def rdma_copy_fused_local(x: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """Fused device->device DMA copy via the *local* async-copy engine — the
    interpret-mode stand-in for the loopback remote copy (on one chip the two
    are the same data movement; the boolean Pallas interpreter supports
    ``make_async_copy`` but not remote descriptors, and the TPU-interpret
    machinery (`InterpretParams`) cannot coexist with pinned-host program
    outputs — probed: mlir memory-kind propagation length mismatch)."""
    if interpret is None:
        interpret = _interpret()
    return pl.pallas_call(
        _loop_local_kernel,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
        compiler_params=_compiler_params(has_side_effects=True),
        interpret=interpret,
        name="rdma_copy_fused_local",
    )(x)


# -- split start/wait (TPU hardware): semaphores as kernel outputs ----------


def _shift_post_kernel(axes, axis, shift, x_ref, send_ref, recv_ref, y_ref):
    """Post half of the mesh neighbor shift: neighbor barrier, then
    ``rdma.start()`` — returns with the DMA in flight (MPI_Isend)."""
    fwd, bwd, id_type, n = _mesh_ids(axes, axis, shift)
    if n > 1:
        barrier = pltpu.get_barrier_semaphore()
        for nb in (fwd, bwd):
            pltpu.semaphore_signal(barrier, inc=1, device_id=nb, device_id_type=id_type)
        pltpu.semaphore_wait(barrier, 2)
    rdma = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=y_ref, send_sem=send_ref, recv_sem=recv_ref,
        device_id=fwd, device_id_type=id_type,
    )
    rdma.start()


def _shift_wait_kernel(axes, axis, shift, x_ref, send_ref, recv_ref, y_in_ref, y_ref):
    """Wait half: block on the posted shift's send+recv semaphores
    (MPI_Wait); the destination passes through aliased."""
    fwd, _, id_type, _ = _mesh_ids(axes, axis, shift)
    rdma = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=y_in_ref, send_sem=send_ref, recv_sem=recv_ref,
        device_id=fwd, device_id_type=id_type,
    )
    rdma.wait()


def rdma_shift_post(
    x: jax.Array,
    axes: Tuple[str, ...],
    axis: Optional[str],
    shift: int,
    collective_id: int = 0,
):
    """Post the mesh neighbor shift; returns (send_sem, recv_sem, y) with the
    remote DMA in flight — the MPI_Isend half of the reference's split
    (ops_mpi.hpp:17-146).  TPU only: the interpreter cannot materialize
    semaphore outputs (probed on v5e; see module docstring)."""
    kern = functools.partial(_shift_post_kernel, tuple(axes), axis, shift)
    needs_barrier = axis is not None and axes and jax.lax.axis_size(axis) > 1
    params = (
        _compiler_params(collective_id=collective_id, has_side_effects=True)
        if needs_barrier
        else _compiler_params(has_side_effects=True)
    )
    return pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.SEMAPHORE),
            pl.BlockSpec(memory_space=pltpu.SEMAPHORE),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        out_shape=(
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            jax.ShapeDtypeStruct(x.shape, x.dtype),
        ),
        compiler_params=params,
        name="rdma_shift_post",
    )(x)


def rdma_shift_wait(
    x: jax.Array, send, recv, y: jax.Array,
    axes: Tuple[str, ...], axis: Optional[str], shift: int,
) -> jax.Array:
    """Block on the in-flight shift's semaphores and return the completed
    destination (aliased, no extra copy) — the MPI_Wait half."""
    kern = functools.partial(_shift_wait_kernel, tuple(axes), axis, shift)
    return pl.pallas_call(
        kern,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SEMAPHORE),
            pl.BlockSpec(memory_space=pltpu.SEMAPHORE),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        input_output_aliases={3: 0},
        compiler_params=_compiler_params(has_side_effects=True),
        name="rdma_shift_wait",
    )(x, send, recv, y)


def rdma_start_loopback(x: jax.Array):
    """Post a device->device RDMA copy of ``x``; returns (send_sem, recv_sem,
    y) with the DMA in flight — the MPI_Isend half.  TPU only (the interpreter
    cannot materialize semaphore outputs; probed).  The degenerate no-axis
    shift: ``_mesh_ids`` yields the LOGICAL self-descriptor and no barrier."""
    return rdma_shift_post(x, (), None, 1)


def rdma_wait_loopback(x: jax.Array, send, recv, y: jax.Array) -> jax.Array:
    """Block on the in-flight copy's semaphores and return the completed
    destination (aliased, no extra copy) — the MPI_Wait half."""
    return rdma_shift_wait(x, send, recv, y, (), None, 1)


# -- schedulable ops --------------------------------------------------------


@register_kind("rdma_copy_start")
class RdmaCopyStart(CommStart):
    """Post a device-resident RDMA copy ``src -> dst`` (loopback on one chip).

    The searchable alternative to the host-staged round trip
    (``HostSpillStart`` + ``HostFetchStart``) in the transfer-engine menu:
    device buffers addressed by the DMA engine, no PCIe/host hop — the
    CUDA-aware-MPI analog (SURVEY §7.0).  On TPU the post stashes a wait
    closure for ``AwaitTransfer`` (split kernels, true Isend/Wait); under the
    interpreter it degrades to the fused kernel."""

    def apply(self, bufs: Dict[str, Any], ctx) -> Dict[str, Any]:
        x = bufs[self._src]
        if _interpret():
            return {self._dst: rdma_copy_fused_local(x)}
        send, recv, y = rdma_start_loopback(x)
        inflight = getattr(ctx, "inflight", None)
        if inflight is not None:
            inflight[self._dst] = functools.partial(
                rdma_wait_loopback, x, send, recv
            )
        return {self._dst: y}

    def uses_pallas(self) -> bool:
        return True


@register_kind("rdma_shift_start")
class RdmaShiftStart(CommStart):
    """Post a neighbor shift of ``src`` over mesh axis ``axis`` into ``dst``
    via per-neighbor remote DMA — the menu alternative to :class:`PermuteStart`
    (XLA collective-permute).  ``collective_id`` must be unique among RDMA
    ops with barriers in one schedule (barrier semaphores are shared by id).

    On TPU the post and the wait are SEPARATE Pallas kernels passing DMA
    semaphores between them (``rdma_shift_post``/``rdma_shift_wait``): this op
    issues the barrier + ``rdma.start()`` and stashes the wait closure for
    ``AwaitTransfer`` — the true MPI_Isend/MPI_Wait split the reference models
    (ops_mpi.hpp:17-146), so the searched post/wait placement is a physical
    overlap freedom on the mesh, not just a graph position (VERDICT r3 item 2).
    Under the Pallas interpreter (CPU tests/dryrun) semaphore outputs are
    unsupported, so the op degrades to the fused start+wait kernel and the
    await falls back to the ordinary data dependency."""

    def __init__(self, name: str, src: str, dst: str, axis: str,
                 shift: int = 1, collective_id: int = 0):
        super().__init__(name, src, dst)
        self._axis = axis
        self._shift = shift
        self._cid = collective_id

    def apply(self, bufs: Dict[str, Any], ctx) -> Dict[str, Any]:
        axes = tuple(getattr(ctx, "axis_names", ()) or ())
        x = bufs[self._src]
        axis = self._axis if axes else None
        if _interpret():
            return {
                self._dst: rdma_shift_fused(
                    x, axes, axis, self._shift, collective_id=self._cid,
                )
            }
        send, recv, y = rdma_shift_post(
            x, axes, axis, self._shift, collective_id=self._cid
        )
        inflight = getattr(ctx, "inflight", None)
        if inflight is not None:
            inflight[self._dst] = functools.partial(
                rdma_shift_wait, x, send, recv,
                axes=axes, axis=axis, shift=self._shift,
            )
        return {self._dst: y}

    def uses_pallas(self) -> bool:
        return True

    def to_json(self) -> Dict[str, Any]:
        j = super().to_json()
        j.update(axis=self._axis, shift=self._shift, collective_id=self._cid)
        return j
