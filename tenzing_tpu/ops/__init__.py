"""Pallas device kernels for the hot ops.

Each kernel has a Pallas TPU path and an ``interpret=True`` path so the same
code runs in CPU tests (SURVEY.md §4: device tests are opt-in; unit tests run
anywhere).  Kernel selection is exposed to the *scheduler* as implementation
ChoiceOps in the workload models (reference ChoiceOp, operation.hpp:90-93) —
picking the faster kernel is part of the searched schedule space.
"""

from tenzing_tpu.ops.spmv_pallas import ell_spmv_pallas

__all__ = ["ell_spmv_pallas"]
