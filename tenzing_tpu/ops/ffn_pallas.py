"""Pallas tiled gelu-MLP kernel: y = gelu(x @ W1) @ W2.

The MXU kernel of the MoE expert (models/moe.py): both matmuls and the gelu
fused into one VMEM-resident pass per row tile, so the hidden activations
h = gelu(x W1) never round-trip through HBM (the fusion XLA usually finds on
its own; doing it in Pallas makes the kernel an honest menu alternative the
search can time, like ops/spmv_pallas.py vs the XLA gather path).

The grid runs over row tiles of x; each program loads one (bm, d) tile plus
both weight matrices (d x dff and dff x d — VMEM-sized for the model dims this
framework targets) and writes one output tile.  Ragged row counts are padded
up to the tile and sliced back off (rows are independent; pad rows compute
finite garbage that is discarded).

``interpret=True`` (automatic off-TPU) runs the kernel in the Pallas
interpreter for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tenzing_tpu.ops.common import out_struct


def _ffn_kernel(x_ref, w1_ref, w2_ref, y_out):
    x = x_ref[...]  # (bm, d)
    h = jax.nn.gelu(
        jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    )
    y_out[...] = jnp.dot(
        h.astype(x.dtype), w2_ref[...], preferred_element_type=jnp.float32
    ).astype(y_out.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ffn_pallas(
    x: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """gelu MLP over row-tiled x: x (n, d), w1 (d, dff), w2 (dff, d) -> (n, d)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    bm = min(n, 512)
    pad = (-n) % bm
    np_ = n + pad
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _ffn_kernel,
        grid=(np_ // bm,),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i: (i, 0)),
            pl.BlockSpec(w1.shape, lambda i: (0, 0)),
            pl.BlockSpec(w2.shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, d), lambda i: (i, 0)),
        out_shape=out_struct((np_, d), x.dtype, x, w1, w2),
        interpret=interpret,
    )(x, w1, w2)
    return out[:n] if pad else out


def _ffn_batched_kernel(x_ref, w1_ref, w2_ref, y_out, acc):
    # cross-k partial sums accumulate in a f32 scratch, cast to the output
    # dtype only once at the last hidden tile — a bf16 caller keeps the f32
    # precision the preferred_element_type matmuls bought (ADVICE r2)
    k = pl.program_id(2)
    x = x_ref[0]  # (bm, d) one expert's row tile
    h = jax.nn.gelu(
        jnp.dot(x, w1_ref[0], preferred_element_type=jnp.float32)
    )
    contrib = jnp.dot(
        h.astype(x.dtype), w2_ref[0], preferred_element_type=jnp.float32
    )

    @pl.when(k == 0)
    def _init():
        acc[...] = contrib

    @pl.when(k != 0)
    def _accum():
        acc[...] += contrib

    @pl.when(k == pl.num_programs(2) - 1)
    def _flush():
        y_out[0] = acc[...].astype(y_out.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ffn_pallas_batched(
    x: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-expert gelu MLP: x (E, C, d), w1 (E, d, dff), w2 (E, dff, d) ->
    (E, C, d), expert e's rows through expert e's weights (the MoE expert
    kernel, models/moe_pipeline.py).

    The grid runs over (expert, row tile, hidden tile): gelu is elementwise,
    so y = sum_k gelu(x @ W1[:, k-th cols]) @ W2[k-th rows, :] decomposes over
    hidden-dim tiles and each program holds one row tile plus one (d, bf) /
    (bf, d) weight-tile pair in VMEM — a whole 512x2048 expert pair plus its
    hidden activations exceeds the 16 MB VMEM scope (measured on v5e).  The
    hidden tile k is the innermost grid axis, so the output block is revisited
    consecutively and accumulated in place."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    e, c, d = x.shape
    dff = w1.shape[2]
    bm = min(c, 256)
    pad = (-c) % bm
    cp = c + pad
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    bf = min(dff, 512)
    fpad = (-dff) % bf
    if fpad:
        # zero-padding the hidden dim is exact: gelu(x @ 0) = gelu(0) row
        # through zero W2 rows contributes 0
        w1 = jnp.pad(w1, ((0, 0), (0, 0), (0, fpad)))
        w2 = jnp.pad(w2, ((0, 0), (0, fpad), (0, 0)))
    out = pl.pallas_call(
        _ffn_batched_kernel,
        grid=(e, cp // bm, (dff + fpad) // bf),
        in_specs=[
            pl.BlockSpec((1, bm, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, d, bf), lambda i, j, k: (i, 0, k)),
            pl.BlockSpec((1, bf, d), lambda i, j, k: (i, k, 0)),
        ],
        out_specs=pl.BlockSpec((1, bm, d), lambda i, j, k: (i, j, 0)),
        out_shape=out_struct((e, cp, d), x.dtype, x, w1, w2),
        scratch_shapes=[pltpu.VMEM((bm, d), jnp.float32)],
        interpret=interpret,
    )(x, w1, w2)
    return out[:, :c] if pad else out
