"""Pallas tiled gelu-MLP kernel: y = gelu(x @ W1) @ W2.

The MXU kernel of the MoE expert (models/moe.py): both matmuls and the gelu
fused into one VMEM-resident pass per row tile, so the hidden activations
h = gelu(x W1) never round-trip through HBM (the fusion XLA usually finds on
its own; doing it in Pallas makes the kernel an honest menu alternative the
search can time, like ops/spmv_pallas.py vs the XLA gather path).

The grid runs over row tiles of x; each program loads one (bm, d) tile plus
both weight matrices (d x dff and dff x d — VMEM-sized for the model dims this
framework targets) and writes one output tile.  Ragged row counts are padded
up to the tile and sliced back off (rows are independent; pad rows compute
finite garbage that is discarded).

``interpret=True`` (automatic off-TPU) runs the kernel in the Pallas
interpreter for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tenzing_tpu.ops.common import out_struct


def _ffn_kernel(x_ref, w1_ref, w2_ref, y_out):
    x = x_ref[...]  # (bm, d)
    h = jax.nn.gelu(
        jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    )
    y_out[...] = jnp.dot(
        h.astype(x.dtype), w2_ref[...], preferred_element_type=jnp.float32
    ).astype(y_out.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ffn_pallas(
    x: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """gelu MLP over row-tiled x: x (n, d), w1 (d, dff), w2 (dff, d) -> (n, d)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    bm = min(n, 512)
    pad = (-n) % bm
    np_ = n + pad
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _ffn_kernel,
        grid=(np_ // bm,),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i: (i, 0)),
            pl.BlockSpec(w1.shape, lambda i: (0, 0)),
            pl.BlockSpec(w2.shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, d), lambda i: (i, 0)),
        out_shape=out_struct((np_, d), x.dtype, x, w1, w2),
        interpret=interpret,
    )(x, w1, w2)
    return out[:n] if pad else out
