"""Pallas ELL-slab SpMV kernel: y = sum(vals * x[cols], axis=1).

TPU-native replacement for the reference's cuSPARSE ``cusparseSpMV`` call
(ops_spmv.cuh:61-163) and hand-rolled CUDA ``spmv`` kernel (ops_spmv.cuh:25-39),
operating on the ELL/band slab built by ``CsrMat.to_slab`` (models/spmv.py).

Hardware note (probed on TPU v5e, jax 0.9 Mosaic): in-kernel dynamic gather
(``tpu.dynamic_gather``) requires operand/indices/output to share one 2D shape
with the gathered (lane) dimension exactly 128 — a within-vreg shuffle.  An
arbitrary-width gather therefore cannot live in the kernel; XLA's native gather
HLO is the hardware path for large x (models/spmv.py SpMVOp).  This kernel
instead decomposes x into 128-lane vregs and accumulates a masked within-vreg
gather per block:

    for b in blocks(x):   # unrolled, n/128 vregs
        g = dyn_gather(broadcast(x[b]), clip(cols - 128*b))   # lane shuffle
        acc += vals * g * (cols in block b)

Cost scales with ``n/128 * m * w`` lane-ops, so it wins only for *small* x —
exactly the renumbered remote-column vector of the distributed SpMV split
(reference split_mat.hpp:22-136: only needed x entries move).  Whether it beats
the XLA gather for a given matrix is an empirical question — so the workload
exposes the choice as a ChoiceOp and the solver searches it (the reference's
ChoiceOp menu, operation.hpp:90-93).

``interpret=True`` (automatic off-TPU) runs the same kernel in the Pallas
interpreter for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tenzing_tpu.ops.common import out_struct

LANES = 128

# n/128 vregs above which the masked-gather sweep is clearly worse than the XLA
# gather path; callers use this to decide whether to even offer the choice
MAX_X_BLOCKS = 32


def supports(n: int, max_blocks: int = MAX_X_BLOCKS) -> bool:
    """Whether the kernel is sensible for an x vector of length ``n``."""
    return n <= LANES * max_blocks


def _ell_kernel(vals_ref, cols_ref, x_ref, o_ref):
    block_m, w = vals_ref.shape
    n_pad = x_ref.shape[1]
    cols = cols_ref[...]
    vals = vals_ref[...]
    acc = jnp.zeros((block_m, 1), vals.dtype)
    for b in range(n_pad // LANES):
        xb = jnp.broadcast_to(x_ref[:, b * LANES : (b + 1) * LANES], (block_m, LANES))
        rel = cols - b * LANES
        in_blk = (rel >= 0) & (rel < LANES)
        g = jnp.take_along_axis(
            xb,
            jnp.clip(rel, 0, LANES - 1),
            axis=1,
            mode="promise_in_bounds",
        )
        acc += jnp.sum(
            jnp.where(in_blk, vals * g, jnp.zeros_like(vals)),
            axis=1,
            keepdims=True,
        )
    o_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("block_m", "interpret"))
def ell_spmv_pallas(
    vals: jax.Array,
    cols: jax.Array,
    x: jax.Array,
    *,
    block_m: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """y[i] = sum_j vals[i, j] * x[cols[i, j]] via the masked vreg-gather kernel."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, w = vals.shape
    n = x.shape[0]
    # pad the slab width to a lane multiple (cols 0 / vals 0: contributes 0)
    w_pad = -(-w // LANES) * LANES
    if w_pad != w:
        vals = jnp.pad(vals, ((0, 0), (0, w_pad - w)))
        cols = jnp.pad(cols, ((0, 0), (0, w_pad - w)))
    n_pad = -(-n // LANES) * LANES
    xp = jnp.pad(x, (0, n_pad - n)) if n_pad != n else x
    block_m = min(block_m, max(8, m))
    m_pad = -(-m // block_m) * block_m
    if m_pad != m:
        vals = jnp.pad(vals, ((0, m_pad - m), (0, 0)))
        cols = jnp.pad(cols, ((0, m_pad - m), (0, 0)))
    y = pl.pallas_call(
        _ell_kernel,
        grid=(m_pad // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((block_m, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((1, n_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, 1), lambda i: (i, 0)),
        out_shape=out_struct((m_pad, 1), vals.dtype, vals, cols, xp),
        interpret=interpret,
    )(vals, cols, xp.reshape(1, n_pad))
    return y[:m, 0]
