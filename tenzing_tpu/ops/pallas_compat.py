"""Pallas version-compat shim: one import site absorbs jax API drift.

The kernels in this package target the current Pallas TPU API, but the
search/serving layers must keep working in containers pinned to older jax
(the CI matrix and the measurement tunnels do not upgrade in lockstep).
Two renames/additions broke every pallas-importing suite on jax 0.4.37:

* ``jax.experimental.pallas.tpu.CompilerParams`` is ``TPUCompilerParams``
  on older jax, and the older dataclass is missing fields newer kernels
  pass (0.4.37 has no ``has_side_effects``).  :func:`compiler_params`
  resolves the class once and **drops unknown kwargs** — the dropped
  fields are compile-time hints (side-effect pinning, megacore grid
  semantics) that only matter on a real TPU backend, which always ships a
  matching jax; the older container only ever runs these kernels in the
  Pallas interpreter, where the hints are inert anyway.
* ``jax.typeof`` (the varying-across-mesh ``vma`` probe ``out_struct``
  uses) does not exist on 0.4.37.  :func:`typeof` falls back to
  ``jax.eval_shape``, whose ShapeDtypeStruct simply carries no ``vma``
  attribute — matching the old behavior where shard_map had no varying
  -axes check to satisfy.

Everything else in the kernels (BlockSpec layout, scratch_shapes,
``pl.when``) is stable across the supported range; add to this module
rather than version-gating at kernel sites.
"""

from __future__ import annotations

import dataclasses
from typing import Any


def compiler_params_cls():
    """The platform's Pallas TPU compiler-params class, whatever its name."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = getattr(pltpu, "TPUCompilerParams")
    return cls


def compiler_params(**kwargs: Any):
    """A compiler-params instance, dropping kwargs the installed jax's class
    does not know (see module docstring for why dropping is sound here)."""
    cls = compiler_params_cls()
    try:
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in known}
    except TypeError:  # not a dataclass on some future jax: pass through
        pass
    return cls(**kwargs)


def typeof(x):
    """``jax.typeof(x)`` where it exists, else a ``jax.eval_shape`` struct
    (no ``vma`` attribute — callers getattr with a default)."""
    import jax

    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.eval_shape(lambda a: a, x)
