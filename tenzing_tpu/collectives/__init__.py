"""Synthesized collectives: searchable chunk-routed p2p decompositions.

The decision space used to pick *which* fixed comm engine runs a collective
(XLA psum vs Pallas RDMA, etc.).  This subsystem decomposes the collectives
THEMSELVES — all-gather / reduce-scatter / all-reduce / all-to-all — into
chunked point-to-point steps over the actual ICI/PCIE topology
(:mod:`~tenzing_tpu.collectives.topology`) and exposes each decomposition as
an ordinary choice-graph alternative (:mod:`~tenzing_tpu.collectives.synth`)
that MCTS, DFS and hill-climb search with zero solver changes.

TACCL-style sketches (PAPERS.md) keep the routing space tractable: only a
few named algorithm shapes (ring, recursive halving/doubling, chunked
neighbor-exchange, staged host pipeline) are ever instantiated, each per
(collective, mesh axis, chunk count, rotation), and a GC3-style alpha-beta
cost per instantiation feeds ``bench/roofline.py::prune_sketches`` so
instantiations that cannot beat the fixed collective's floor never enter the
menus.  PR 10's ``ChunkedOp`` is the template throughout: a synthesized
collective is "chunking for comm ops" — a directive + real transfer steps +
local-combine RMW partials, certified by the verifier as-is.
"""

from tenzing_tpu.collectives.synth import (  # noqa: F401
    SKETCHES,
    SYNTH_MARK,
    FixedCollective,
    SynthCollectiveChoice,
    SynthCollectiveOp,
    SynthDirective,
    SynthPlan,
    plan_host_pipe,
    plan_neighbor_shift,
    plan_rhd_all_reduce,
    plan_ring_all_reduce,
    plan_ring_all_to_all,
    sketch_menu,
    synth_hidden_comm_measured_us,
    synth_menus,
    synths_of,
)
from tenzing_tpu.collectives.topology import (  # noqa: F401
    Link,
    Topology,
    engine_of_kind,
    host_topology,
    mesh_topology,
    ring_topology,
)
