"""TACCL-style sketch synthesis: collectives as searchable p2p decompositions.

The decision space used to pick *which* fixed engine runs a collective
(``PsumStart`` vs RDMA, XLA permute vs Pallas).  This module decomposes the
collective ITSELF: a sketch (ring, reverse ring, recursive halving/doubling,
chunked neighbor-exchange, staged host pipeline) instantiated per
(collective, mesh axis, chunk count, rotation) becomes a
:class:`SynthCollectiveOp` — an ordinary ``CompoundOp`` whose sub-graph is a
chain of REAL point-to-point transfer steps (``PermuteStart`` hops over ICI,
``HostSpillStart``/``HostFetchStart`` over PCIE) plus local-combine RMW
partials (``AddInto``/``PlaceSlice``), each step carrying true data deps.
PR 10's ``ChunkedOp`` is the template decision-for-decision: directive entry
vertex, serial per-chunk chains, combine folded into accumulating updates,
certified by the PR 4 verifier as-is and searched by MCTS/DFS/hill-climb
through the ordinary ``ChooseOp`` machinery with ZERO solver changes.

Sketches (the TACCL tractability constraint — only these shapes are ever
instantiated):

* ``ring`` / ``ringr`` — all-reduce: each chunk's accumulator circulates the
  axis ring (forward / reverse rotation), adding the rotating partial each
  hop; ``n-1`` hops of ``B/k`` bytes per chunk.
* ``rhd`` — recursive halving/doubling all-reduce (power-of-two axes): the
  accumulator itself permutes by doubling shifts ``1, 2, 4, ...`` —
  ``log2(n)`` hops of ``B`` bytes, the latency-optimal tree shape.
* ``neighbor`` — chunked neighbor-exchange (halo shifts): the face payload
  splits into ``k`` chunk transfers whose awaits interleave.
* ``pipe`` — staged host pipeline (PCIE): the payload round-trips
  device->host->device in ``k`` chunks so fetch ``j`` overlaps spill
  ``j+1`` — chunk routing over the host link.

Every instantiation is priced by a GC3-style alpha-beta walk over the
explicit :mod:`~tenzing_tpu.collectives.topology` links and pruned against
the fixed collective's floor (``bench/roofline.py::prune_sketches``) before
it ever enters a menu.  Numerics follow the chunking contract
(docs/performance.md): pure-movement instantiations (``pipe``/``neighbor``,
any ``k``) are bit-identical; synthesized reductions re-associate the sum
and are held to the driver's allclose integrity gate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence as Seq, Tuple

from tenzing_tpu.collectives.topology import Topology
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    ChoiceOp,
    CompoundOp,
    CpuOp,
    DeviceOp,
    OpBase,
    register_kind,
)
from tenzing_tpu.ops.comm_ops import (
    AwaitTransfer,
    HostFetchStart,
    HostSpillStart,
    PermuteStart,
)

# the directive marker: a SynthDirective is named
# f"{base}{SYNTH_MARK}{sketch}.c{k}".  learn/features.py duplicates this
# string and the sketch tuple (importing nothing from here so the featurizer
# stays jax-free); tests/test_collectives.py asserts they agree.
SYNTH_MARK = ".synth."

#: The sketch vocabulary — the TACCL-style constraint that keeps the space
#: tractable: nothing outside this tuple is ever instantiated.
SKETCHES = ("ring", "ringr", "rhd", "neighbor", "pipe")

#: What each sketch decomposes, for provenance blocks.
COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "shift", "exchange")


@register_kind("synth")
class SynthDirective(CpuOp):
    """The executed synthesis directive: a no-op host op named
    ``<base>.synth.<sketch>.c<K>`` riding the schedule so the chosen sketch
    and chunk count are readable from the executed op list — the synth twin
    of ``ChunkDirective`` (``<base>.chunk.cN``) and ``fuse_tile.tN``."""

    def __init__(self, base: str, sketch: str, chunks: int):
        if sketch not in SKETCHES:
            raise ValueError(f"unknown sketch {sketch!r} (have {SKETCHES})")
        super().__init__(f"{base}{SYNTH_MARK}{sketch}.c{int(chunks)}")
        self._base = base
        self._sketch = sketch
        self._chunks = int(chunks)

    def base(self) -> str:
        return self._base

    def sketch(self) -> str:
        return self._sketch

    def chunks(self) -> int:
        return self._chunks

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(), "base": self._base,
                "sketch": self._sketch, "chunks": self._chunks}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "SynthDirective":
        return cls(j["base"], j["sketch"], int(j["chunks"]))


# ---------------------------------------------------------------------------
# step ops: the local halves of a p2p decomposition.  All compute row
# extents from the RUNTIME shape (the TpLayerRowsPartial discipline) so the
# same graph traces correctly under dp-sharded layouts.
# ---------------------------------------------------------------------------


class SlicePick(DeviceOp):
    """``dst = src[chunk j of k]`` along axis 0 — the chunk extraction that
    feeds a p2p hop.  ``k=1`` is a whole-buffer copy (pure movement)."""

    def __init__(self, name: str, src: str, dst: str, part: int, n_parts: int):
        super().__init__(name)
        self._src, self._dst = src, dst
        self._part, self._n = int(part), int(n_parts)

    def reads(self) -> List[str]:
        return [self._src]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        from jax import lax

        x = bufs[self._src]
        rows = x.shape[0]
        if rows % self._n:
            raise ValueError(
                f"{self.name()}: {rows} runtime rows do not split {self._n} ways")
        sz = rows // self._n
        return {self._dst: lax.dynamic_slice_in_dim(x, self._part * sz, sz, 0)}


class PlaceSlice(DeviceOp):
    """RMW ``dst[chunk j of k] = piece`` along axis 0 — the combine fold:
    each chain deposits its finished chunk into the collective's output
    buffer by an accumulating slice update (disjoint slices, any order)."""

    def __init__(self, name: str, piece: str, dst: str, part: int, n_parts: int):
        super().__init__(name)
        self._piece, self._dst = piece, dst
        self._part, self._n = int(part), int(n_parts)

    def reads(self) -> List[str]:
        return [self._piece, self._dst]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        from jax import lax

        dst = bufs[self._dst]
        rows = dst.shape[0]
        if rows % self._n:
            raise ValueError(
                f"{self.name()}: {rows} runtime rows do not split {self._n} ways")
        lo = self._part * (rows // self._n)
        return {self._dst: lax.dynamic_update_slice_in_dim(
            dst, bufs[self._piece], lo, 0)}


class AddInto(DeviceOp):
    """RMW ``acc += piece`` — the reduction partial every all-reduce sketch
    folds its arriving hop into (re-associates the sum; allclose-gated)."""

    def __init__(self, name: str, piece: str, acc: str):
        super().__init__(name)
        self._piece, self._acc = piece, acc

    def reads(self) -> List[str]:
        return [self._piece, self._acc]

    def writes(self) -> List[str]:
        return [self._acc]

    def apply(self, bufs, ctx):
        return {self._acc: bufs[self._acc] + bufs[self._piece]}


class ConcatPieces(DeviceOp):
    """``dst = concat(pieces, axis 0)`` — the pipe sketch's reassembly of
    its staged chunks (pure movement: bit-identical for any k)."""

    def __init__(self, name: str, pieces: Seq[str], dst: str):
        super().__init__(name)
        self._pieces = list(pieces)
        self._dst = dst

    def reads(self) -> List[str]:
        return list(self._pieces)

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        return {self._dst: jnp.concatenate(
            [bufs[p] for p in self._pieces], axis=0)}


class StaticSlice(DeviceOp):
    """``dst = src[lo:lo+size]`` with build-time bounds — the pipe sketch's
    chunk extraction, where uneven remainders make runtime division wrong."""

    def __init__(self, name: str, src: str, dst: str, lo: int, size: int):
        super().__init__(name)
        self._src, self._dst = src, dst
        self._lo, self._size = int(lo), int(size)

    def reads(self) -> List[str]:
        return [self._src]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        from jax import lax

        return {self._dst: lax.dynamic_slice_in_dim(
            bufs[self._src], self._lo, self._size, 0)}


class RowPick(DeviceOp):
    """``dst = src[(axis_index + off) % n]`` (one peer row, kept 3-D) — the
    all-to-all ring's send selection: at rotation step ``s`` every shard
    picks the row destined for the peer ``s`` hops ahead."""

    def __init__(self, name: str, src: str, dst: str, off: int, axis: str):
        super().__init__(name)
        self._src, self._dst = src, dst
        self._off, self._axis = int(off), axis

    def reads(self) -> List[str]:
        return [self._src]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        import jax
        from jax import lax

        n = jax.lax.axis_size(self._axis)
        i = (lax.axis_index(self._axis) + self._off) % n
        return {self._dst: lax.dynamic_slice_in_dim(bufs[self._src], i, 1, 0)}


class RowPlace(DeviceOp):
    """RMW ``dst[(axis_index + off) % n] = piece`` — the all-to-all ring's
    receive deposit: the row that arrived from ``-off`` hops back lands at
    its sender's index (disjoint rows across steps, any order)."""

    def __init__(self, name: str, piece: str, dst: str, off: int, axis: str):
        super().__init__(name)
        self._piece, self._dst = piece, dst
        self._off, self._axis = int(off), axis

    def reads(self) -> List[str]:
        return [self._piece, self._dst]

    def writes(self) -> List[str]:
        return [self._dst]

    def apply(self, bufs, ctx):
        import jax
        from jax import lax

        n = jax.lax.axis_size(self._axis)
        i = (lax.axis_index(self._axis) + self._off) % n
        return {self._dst: lax.dynamic_update_slice_in_dim(
            bufs[self._dst], bufs[self._piece], i, 0)}


# ---------------------------------------------------------------------------
# plans: one instantiated sketch = chains of real steps + staging decls +
# an alpha-beta transfer census.  The plan is the single source of truth
# consumed by BOTH the graph builder (op chains) and the model's buffer
# builder (staging decls), so names and shapes cannot drift apart.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BufDecl:
    """One staging buffer a plan needs: per-shard shape; ``space="host"``
    decls must be placed pinned-host by the model's buffer builder."""

    name: str
    shape: Tuple[int, ...]
    space: str = "device"


@dataclass
class SynthPlan:
    """One (collective, sketch, chunk count, rotation) instantiation."""

    base: str
    collective: str
    sketch: str
    chunks: int
    chains: List[List[OpBase]] = field(default_factory=list)
    combines: List[OpBase] = field(default_factory=list)
    buffers: List[BufDecl] = field(default_factory=list)
    engine: str = "ici"
    n_xfers: int = 0  # separately posted p2p transfers
    xfer_bytes: float = 0.0  # total bytes moved across them

    def label(self) -> str:
        return f"{self.sketch}.c{self.chunks}"


def _chunk_ranges(length: int, k: int) -> List[Tuple[int, int]]:
    """k contiguous [lo, hi) ranges covering ``length`` (remainder spread
    over the head chunks) — the uneven-split recipe of spmv's row part."""
    q, r = divmod(int(length), int(k))
    out, lo = [], 0
    for j in range(k):
        sz = q + (1 if j < r else 0)
        out.append((lo, lo + sz))
        lo += sz
    return out


def plan_ring_all_reduce(base: str, src: str, dst: str, axis: str,
                         n_axis: int, part_shape: Seq[int], k: int,
                         itemsize: int = 4,
                         reverse: bool = False) -> SynthPlan:
    """Chunked ring all-reduce over one ICI axis: chunk ``j``'s accumulator
    seeds from the local slice, then ``n-1`` rotating hops each deliver a
    peer's slice to fold in (``ringr`` rotates the other way — same cost,
    different link direction and interleave freedom)."""
    rows = int(part_shape[0])
    if k < 1 or rows % k:
        raise ValueError(f"{base}: {rows} rows do not split {k} ways")
    if n_axis < 2:
        raise ValueError(f"{base}: ring needs an axis extent >= 2")
    sketch = "ringr" if reverse else "ring"
    shift = -1 if reverse else 1
    pre = f"{base}.{sketch}{k}"
    cshape = (rows // k,) + tuple(int(s) for s in part_shape[1:])
    cbytes = float(itemsize)
    for s in cshape:
        cbytes *= s
    plan = SynthPlan(base, "all_reduce", sketch, k, engine="ici",
                     n_xfers=k * (n_axis - 1),
                     xfer_bytes=k * (n_axis - 1) * cbytes)
    for j in range(k):
        cur, acc = f"{pre}.x{j}.cur", f"{pre}.x{j}.acc"
        plan.buffers += [BufDecl(cur, cshape), BufDecl(acc, cshape)]
        chain: List[OpBase] = [
            SlicePick(f"{pre}.x{j}.pick", src, cur, j, k),
            SlicePick(f"{pre}.x{j}.seed", src, acc, j, k),
        ]
        prev = cur
        for s in range(1, n_axis):
            rot = f"{pre}.x{j}.rot{s}"
            plan.buffers.append(BufDecl(rot, cshape))
            chain += [
                PermuteStart(f"{pre}.x{j}.p{s}", prev, rot, axis, shift),
                AwaitTransfer(f"{pre}.x{j}.w{s}", rot),
                AddInto(f"{pre}.x{j}.add{s}", rot, acc),
            ]
            prev = rot
        chain.append(PlaceSlice(f"{pre}.x{j}.put", acc, dst, j, k))
        plan.chains.append(chain)
    return plan


def plan_rhd_all_reduce(base: str, src: str, dst: str, axis: str,
                        n_axis: int, part_shape: Seq[int],
                        itemsize: int = 4) -> SynthPlan:
    """Recursive halving/doubling all-reduce (power-of-two axes): the
    accumulator itself permutes by shifts ``1, 2, 4, ...`` and folds each
    arrival — after ``log2(n)`` hops every shard holds the full sum.  The
    latency-optimal shape: ``log2(n)`` posts instead of the ring's
    ``k*(n-1)``, at full payload bytes per hop."""
    if n_axis < 2 or n_axis & (n_axis - 1):
        raise ValueError(f"{base}: rhd needs a power-of-two axis, got {n_axis}")
    pre = f"{base}.rhd1"
    cshape = tuple(int(s) for s in part_shape)
    cbytes = float(itemsize)
    for s in cshape:
        cbytes *= s
    import math

    hops = int(math.log2(n_axis))
    plan = SynthPlan(base, "all_reduce", "rhd", 1, engine="ici",
                     n_xfers=hops, xfer_bytes=hops * cbytes)
    acc = f"{pre}.acc"
    plan.buffers.append(BufDecl(acc, cshape))
    chain: List[OpBase] = [SlicePick(f"{pre}.seed", src, acc, 0, 1)]
    s = 1
    while s < n_axis:
        rot = f"{pre}.rot{s}"
        plan.buffers.append(BufDecl(rot, cshape))
        chain += [
            PermuteStart(f"{pre}.p{s}", acc, rot, axis, s),
            AwaitTransfer(f"{pre}.w{s}", rot),
            AddInto(f"{pre}.add{s}", rot, acc),
        ]
        s *= 2
    chain.append(PlaceSlice(f"{pre}.put", acc, dst, 0, 1))
    plan.chains.append(chain)
    return plan


def plan_ring_all_to_all(base: str, src: str, dst: str, axis: str,
                         n_axis: int, row_shape: Seq[int],
                         itemsize: int = 4) -> SynthPlan:
    """Ring all-to-all over one ICI axis: rotation step ``s`` picks the row
    destined ``s`` hops ahead, permutes it there in one hop, and deposits
    it at the sender's index — ``n-1`` single-hop posts replace the fused
    ``AllToAllStart``, and their awaits interleave with other work.  Pure
    movement (bit-identical): matches ``lax.all_to_all`` row-for-row."""
    if n_axis < 2:
        raise ValueError(f"{base}: a2a ring needs an axis extent >= 2")
    pre = f"{base}.ring1"
    rshape = (1,) + tuple(int(s) for s in row_shape)
    rbytes = float(itemsize)
    for s in rshape:
        rbytes *= s
    plan = SynthPlan(base, "all_to_all", "ring", 1, engine="ici",
                     n_xfers=n_axis - 1, xfer_bytes=(n_axis - 1) * rbytes)
    own = f"{pre}.x0.row"
    plan.buffers.append(BufDecl(own, rshape))
    plan.chains.append([
        RowPick(f"{pre}.x0.pick", src, own, 0, axis),
        RowPlace(f"{pre}.x0.put", own, dst, 0, axis),
    ])
    for s in range(1, n_axis):
        row, mv = f"{pre}.x{s}.row", f"{pre}.x{s}.mv"
        plan.buffers += [BufDecl(row, rshape), BufDecl(mv, rshape)]
        plan.chains.append([
            RowPick(f"{pre}.x{s}.pick", src, row, s, axis),
            PermuteStart(f"{pre}.x{s}.p", row, mv, axis, s),
            AwaitTransfer(f"{pre}.x{s}.w", mv),
            RowPlace(f"{pre}.x{s}.put", mv, dst, -s, axis),
        ])
    return plan


def plan_neighbor_shift(base: str, src: str, dst: str, axis: str,
                        shift: int, part_shape: Seq[int], k: int,
                        itemsize: int = 4) -> SynthPlan:
    """Chunked neighbor-exchange (the halo shift): the face payload splits
    into ``k`` chunk permutes whose awaits interleave — chunk routing for a
    single-hop shift.  Pure movement (bit-identical for any k)."""
    rows = int(part_shape[0])
    if k < 1 or rows % k:
        raise ValueError(f"{base}: {rows} rows do not split {k} ways")
    pre = f"{base}.neighbor{k}"
    cshape = (rows // k,) + tuple(int(s) for s in part_shape[1:])
    cbytes = float(itemsize)
    for s in cshape:
        cbytes *= s
    plan = SynthPlan(base, "shift", "neighbor", k, engine="ici",
                     n_xfers=k, xfer_bytes=k * cbytes)
    for j in range(k):
        snd, mv = f"{pre}.x{j}.snd", f"{pre}.x{j}.mv"
        plan.buffers += [BufDecl(snd, cshape), BufDecl(mv, cshape)]
        plan.chains.append([
            SlicePick(f"{pre}.x{j}.pick", src, snd, j, k),
            PermuteStart(f"{pre}.x{j}.p", snd, mv, axis, shift),
            AwaitTransfer(f"{pre}.x{j}.w", mv),
            PlaceSlice(f"{pre}.x{j}.put", mv, dst, j, k),
        ])
    return plan


def plan_host_pipe(base: str, src: str, dst: str, length: int, k: int,
                   itemsize: int = 4) -> SynthPlan:
    """Staged host pipeline over the PCIE link: the payload round-trips
    device->host->device in ``k`` chunks (uneven remainders spread over the
    head chunks), so chunk ``j``'s fetch overlaps chunk ``j+1``'s spill —
    the exact staging discipline chunk routing buys on the host link.
    Pure movement (bit-identical for any k); reassembled by one concat."""
    if k < 1 or k > max(1, int(length)):
        raise ValueError(f"{base}: cannot pipe {length} rows in {k} chunks")
    pre = f"{base}.pipe{k}"
    plan = SynthPlan(base, "exchange", "pipe", k, engine="pcie",
                     n_xfers=2 * k, xfer_bytes=2.0 * length * itemsize)
    pieces: List[str] = []
    for j, (lo, hi) in enumerate(_chunk_ranges(length, k)):
        snd, hst, rcv = f"{pre}.x{j}.snd", f"{pre}.x{j}.hst", f"{pre}.x{j}.rcv"
        plan.buffers += [BufDecl(snd, (hi - lo,)),
                         BufDecl(hst, (hi - lo,), space="host"),
                         BufDecl(rcv, (hi - lo,))]
        plan.chains.append([
            StaticSlice(f"{pre}.x{j}.pick", src, snd, lo, hi - lo),
            HostSpillStart(f"{pre}.x{j}.spill", snd, hst),
            HostFetchStart(f"{pre}.x{j}.fetch", hst, rcv),
            AwaitTransfer(f"{pre}.x{j}.w", rcv),
        ])
        pieces.append(rcv)
    plan.combines.append(ConcatPieces(f"{pre}.cat", pieces, dst))
    return plan


# ---------------------------------------------------------------------------
# graph packaging: plan -> CompoundOp / ChoiceOp, the PR 10 shapes.
# ---------------------------------------------------------------------------


class SynthCollectiveOp(CompoundOp):
    """One instantiated sketch as an ordinary CompoundOp: the
    ``synth.<sketch>.c<K>`` directive fans out into the plan's per-chunk
    chains (serial within a chain — every step reads what its predecessor
    wrote; free across chains — the interleave the search exploits), joined
    by the plan's combine ops.  The scheduler inlines it through
    ``Graph.clone_but_expand`` exactly like ``ChunkedOp``; ``est_us``
    carries the alpha-beta estimate into ``perf.synth``."""

    def __init__(self, plan: SynthPlan, est_us: Optional[float] = None):
        super().__init__(f"{plan.base}.synthed.{plan.sketch}.c{plan.chunks}")
        self._plan = plan
        self.est_us = est_us

    def plan(self) -> SynthPlan:
        return self._plan

    def base(self) -> str:
        return self._plan.base

    def sketch(self) -> str:
        return self._plan.sketch

    def chunks(self) -> int:
        return self._plan.chunks

    def graph(self) -> Graph:
        p = self._plan
        g = Graph()
        d = SynthDirective(p.base, p.sketch, p.chunks)
        g.start_then(d)
        tails: List[OpBase] = []
        for chain in p.chains:
            prev: OpBase = d
            for op in chain:
                g.then(prev, op)
                prev = op
            tails.append(prev)
        if p.combines:
            prev_c: Optional[OpBase] = None
            for cop in p.combines:
                for t in tails:
                    g.then(t, cop)
                if prev_c is not None:
                    g.then(prev_c, cop)
                prev_c = cop
            g.then_finish(prev_c)
        else:
            for t in tails:
                g.then_finish(t)
        return g

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(),
                "base": self._plan.base, "sketch": self._plan.sketch,
                "chunks": self._plan.chunks}


class FixedCollective(CompoundOp):
    """The fixed-engine alternative packaged for a
    :class:`SynthCollectiveChoice`: the site's existing op chain (e.g.
    ``PsumStart -> AwaitTransfer``), serial and unchanged — choosing it
    executes exactly the ops the un-synthesized graph would, preserving
    the bit-identity of the first-choice schedule."""

    def __init__(self, base: str, ops: Seq[OpBase]):
        super().__init__(f"{base}.fixed")
        if not ops:
            raise ValueError(f"{base}: FixedCollective needs at least one op")
        self._ops = list(ops)

    def ops(self) -> List[OpBase]:
        return list(self._ops)

    def graph(self) -> Graph:
        g = Graph()
        prev: Optional[OpBase] = None
        for op in self._ops:
            if prev is None:
                g.start_then(op)
            else:
                g.then(prev, op)
            prev = op
        g.then_finish(prev)
        return g


class SynthCollectiveChoice(ChoiceOp):
    """The synthesized-collective menu for a site with no pre-existing
    engine ChoiceOp: the fixed chain vs the surviving sketch
    instantiations, named ``<base>.synth`` so the choice vertex never
    collides with an executed op name.  Sites that already offer an engine
    menu (halo's ``ExchangeChoice``) append :class:`SynthCollectiveOp`
    variants to that menu instead, so the engine menu and the synthesized
    menu compete in ONE ``ChooseOp``."""

    def __init__(self, base: str, fixed: FixedCollective,
                 variants: Seq[SynthCollectiveOp],
                 menu: Optional[Dict[str, Any]] = None):
        super().__init__(base + ".synth")
        self._fixed = fixed
        self._variants = list(variants)
        if menu is not None:
            self.synth_menu = menu

    def choices(self) -> List[OpBase]:
        return [self._fixed] + list(self._variants)


# ---------------------------------------------------------------------------
# pricing + menus: alpha-beta cost over topology links, roofline prune,
# provenance read-back.
# ---------------------------------------------------------------------------


def _engine_link(topo: Topology, engine: str):
    for l in topo.links:
        if l.engine == engine:
            return l
    return None


def sketch_cost_us(plan: SynthPlan, topo: Topology) -> Optional[float]:
    """GC3-style analytic cost of one instantiation: every posted transfer
    pays its link's alpha, every byte pays the link's beta — a serial
    walk over the plan's transfer census (pipelining upside is the prune
    rule's ``overlap`` credit, not baked into the estimate)."""
    link = _engine_link(topo, plan.engine)
    if link is None:
        return None
    return plan.n_xfers * link.alpha_us + plan.xfer_bytes * link.beta_us_per_byte


def synth_menu_info(base: str, collective: str, menu: Seq[str],
                    est_us: Dict[str, float], pruned: Dict[str, str],
                    fixed_floor_us: Optional[float],
                    note: str) -> Dict[str, Any]:
    """The ``synth_menu`` attribute choice nodes carry for provenance —
    the synth twin of ``chunking.menu_info``.  ``menu`` always leads with
    ``"fixed"``; ``note`` is the non-empty prune explanation the driver's
    ``perf.synth`` block surfaces."""
    return {"base": base, "collective": collective,
            "menu": ["fixed"] + [m for m in menu if m != "fixed"],
            "est_us": {k: float(v) for k, v in est_us.items()},
            "pruned": dict(pruned),
            "fixed_floor_us": (None if fixed_floor_us is None
                               else float(fixed_floor_us)),
            "note": note or "no candidates priced"}


def sketch_menu(plans: Seq[SynthPlan], topo: Topology, fixed_bytes: float,
                overlap_us: float = 0.0, relax: bool = False,
                collective: Optional[str] = None
                ) -> Tuple[List[SynthCollectiveOp], Dict[str, Any]]:
    """Price ``plans`` over ``topo`` links, prune against the fixed
    collective's one-post floor (``roofline.prune_sketches``), and return
    (surviving variants, ``synth_menu`` provenance dict).

    ``fixed_bytes`` is the payload the fixed engine moves in one post;
    ``overlap_us`` the neighboring compute a pipelined instantiation could
    hide under (the GC3 credit).  ``relax=True`` (tests / toy smoke
    shapes, the ``chunk_relax`` twin) keeps every candidate searchable but
    still reports what the analytic rule would have dropped."""
    from tenzing_tpu.bench import roofline

    if not plans:
        return [], synth_menu_info(
            "", collective or "", [], {}, {}, None,
            "no sketch instantiations apply at this site")
    base = plans[0].base
    coll = collective or plans[0].collective
    est: Dict[str, float] = {}
    cands: Dict[str, Dict[str, Any]] = {}
    by_label: Dict[str, SynthPlan] = {}
    for p in plans:
        c = sketch_cost_us(p, topo)
        if c is None:
            continue
        est[p.label()] = c
        cands[p.label()] = {"est_us": c, "steps": p.n_xfers, "chunks": p.chunks}
        by_label[p.label()] = p
    link = _engine_link(topo, plans[0].engine)
    fixed_floor = link.cost_us(fixed_bytes) if link is not None else 0.0
    kept, pruned = roofline.prune_sketches(cands, fixed_floor,
                                           overlap_us=overlap_us)
    if relax:
        note = (f"relax: all {len(cands)} instantiation(s) kept searchable; "
                f"analytic prune vs the fixed floor ({fixed_floor:.1f}us) "
                f"would keep {len(kept)} — advisory reasons in 'pruned'")
        kept = list(cands)
    else:
        note = (f"{len(pruned)} of {len(cands)} instantiation(s) pruned vs "
                f"the fixed one-post floor ({fixed_floor:.1f}us); "
                f"{len(kept)} kept")
    variants = [SynthCollectiveOp(by_label[lbl], est_us=est.get(lbl))
                for lbl in kept]
    menu = synth_menu_info(base, coll, [v.plan().label() for v in variants],
                           est, pruned, fixed_floor, note)
    return variants, menu


def synths_of(order) -> Dict[str, Dict[str, Any]]:
    """The synthesized decompositions an executed schedule carries, by
    site base name (``{}`` for a fixed-engine schedule) — parsed from the
    ``<base>.synth.<sketch>.c<K>`` directives, the read-back twin of
    ``chunking.chunks_of``."""
    out: Dict[str, Dict[str, Any]] = {}
    for op in order:
        name = op.name() if hasattr(op, "name") else str(op)
        i = name.rfind(SYNTH_MARK)
        if i < 0:
            continue
        rest = name[i + len(SYNTH_MARK):]
        sketch, sep, cpart = rest.rpartition(".c")
        if not sep or sketch not in SKETCHES:
            continue
        try:
            out[name[:i]] = {"sketch": sketch, "chunks": max(1, int(cpart))}
        except ValueError:
            continue
    return out


def synth_menus(graph: Graph) -> Dict[str, Dict[str, Any]]:
    """Every synthesized-collective menu a choice graph offers, keyed by
    site base name: walks vertices recursively (compound sub-graphs,
    choice alternatives — the serdes descent) collecting the
    ``synth_menu`` attribute, mirroring ``chunking.chunk_menus``."""
    menus: Dict[str, Dict[str, Any]] = {}
    seen: set = set()

    def visit(op: OpBase) -> None:
        key = id(op)
        if key in seen:
            return
        seen.add(key)
        menu = getattr(op, "synth_menu", None)
        if isinstance(menu, dict) and menu.get("base"):
            menus[menu["base"]] = menu
        if isinstance(op, CompoundOp):
            for v in op.graph().vertices():
                visit(v)
        if isinstance(op, ChoiceOp):
            for c in op.choices():
                visit(c)

    for v in graph.vertices():
        visit(v)
    return menus


def synth_hidden_comm_measured_us(ops, attrib) -> float:
    """Measured hidden comm of a synthesized schedule: total Gantt-interval
    overlap between the chosen decomposition's transfer steps and every
    non-synth compute unit, from the attribution profiler's stepped
    timeline — the ``perf.synth`` twin of
    ``chunking.hidden_comm_measured_us`` (what the chunk routing actually
    ran under neighboring compute)."""
    from tenzing_tpu.bench.model import ICI_KINDS, PCIE_KINDS

    chosen = synths_of(ops)
    if not chosen:
        return 0.0
    ops = list(ops)
    step_prefixes = tuple(
        f"{base}.{v['sketch']}{v['chunks']}." for base, v in chosen.items())
    comm_kinds = set(ICI_KINDS) | set(PCIE_KINDS) | {
        "await_transfer", "multi_await"}

    def op_kind(pos: int) -> str:
        if pos >= len(ops):
            return ""
        op = ops[pos]
        base = op.unbound() if hasattr(op, "unbound") else op
        return getattr(base, "KIND", "") or ""

    xfers: List[Tuple[float, float]] = []
    compute: List[Tuple[float, float]] = []
    for rec in attrib.timeline.records:
        if rec.dur_us <= 0:
            continue
        is_step = rec.name.startswith(step_prefixes)
        is_comm = any(op_kind(p) in comm_kinds for p in rec.positions)
        if is_step and is_comm:
            xfers.append((rec.start_us, rec.end_us))
        elif not is_step and not is_comm:
            compute.append((rec.start_us, rec.end_us))
    total = 0.0
    for cs, ce in xfers:
        for ps, pe in compute:
            total += max(0.0, min(ce, pe) - max(cs, ps))
    return total


__all__ = [
    "SYNTH_MARK", "SKETCHES", "COLLECTIVES",
    "SynthDirective", "SynthPlan", "BufDecl",
    "SlicePick", "PlaceSlice", "AddInto", "ConcatPieces", "StaticSlice",
    "RowPick", "RowPlace",
    "plan_ring_all_reduce", "plan_rhd_all_reduce", "plan_ring_all_to_all",
    "plan_neighbor_shift", "plan_host_pipe",
    "SynthCollectiveOp", "FixedCollective", "SynthCollectiveChoice",
    "sketch_cost_us", "sketch_menu", "synth_menu_info",
    "synths_of", "synth_menus", "synth_hidden_comm_measured_us",
]
