"""Explicit link graph for synthesized collectives.

``bench/model.py`` already classifies every comm-op kind into an engine
queue (ICI vs PCIE) and carries the per-engine alpha-beta parameters in
``ModelEnv``.  This module turns that implicit knowledge into an explicit
topology object: named device nodes per mesh axis, directed ``Link`` edges
(ring ICI neighbors per axis, a PCIE staging link between each device and
its host), and per-link alpha-beta costs in microseconds.  Sketch
instantiation (:mod:`~tenzing_tpu.collectives.synth`) walks these links to
price every (collective, axis, chunk count, rotation) candidate before the
roofline prune, so the menu the solvers see is derived from the same cost
surface the analytic benchmarker charges at measurement time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

from tenzing_tpu.bench.model import ICI_KINDS, PCIE_KINDS, ModelEnv

#: Engine labels, matching ``bench/model.py``'s queue names.
ENGINES = ("ici", "pcie")

#: Node label for the host end of a PCIE staging link.
HOST_NODE = "host"


def engine_of_kind(kind: str) -> Optional[str]:
    """Map a registered comm-op kind onto its engine queue, or ``None``."""
    if kind in ICI_KINDS:
        return "ici"
    if kind in PCIE_KINDS:
        return "pcie"
    return None


@dataclass(frozen=True)
class Link:
    """One directed point-to-point link with an alpha-beta cost model."""

    src: str
    dst: str
    engine: str  # "ici" | "pcie"
    alpha_us: float  # per-transfer post latency
    beta_us_per_byte: float  # inverse bandwidth

    def cost_us(self, nbytes: float) -> float:
        return self.alpha_us + float(nbytes) * self.beta_us_per_byte


def ici_link_params(env: Optional[ModelEnv] = None) -> Tuple[float, float]:
    """(alpha_us, beta_us_per_byte) of one ICI hop, from ``ModelEnv``."""
    env = env or ModelEnv()
    return env.ici_lat * 1e6, 1e6 / env.ici_bw


def pcie_link_params(env: Optional[ModelEnv] = None) -> Tuple[float, float]:
    """(alpha_us, beta_us_per_byte) of the host staging path.

    The analytic model charges PCIE pure bandwidth; the post latency is
    folded into the per-op overhead, which we surface as alpha here so a
    staged pipeline pays per-chunk dispatch like the real executor does.
    """
    env = env or ModelEnv()
    return env.op_overhead * 1e6, 1e6 / env.pcie_bw


@dataclass(frozen=True)
class Topology:
    """A set of directed links plus node bookkeeping."""

    links: Tuple[Link, ...] = field(default_factory=tuple)

    def nodes(self) -> List[str]:
        seen: Dict[str, None] = {}
        for l in self.links:
            seen.setdefault(l.src)
            seen.setdefault(l.dst)
        return list(seen)

    def out_links(self, src: str) -> List[Link]:
        return [l for l in self.links if l.src == src]

    def link(self, src: str, dst: str) -> Optional[Link]:
        for l in self.links:
            if l.src == src and l.dst == dst:
                return l
        return None

    def engines(self) -> List[str]:
        out = []
        for l in self.links:
            if l.engine not in out:
                out.append(l.engine)
        return out

    def merged(self, other: "Topology") -> "Topology":
        return Topology(self.links + other.links)

    def min_hops(self, src: str, dst: str) -> int:
        """BFS hop count between two nodes; -1 when unreachable."""
        if src == dst:
            return 0
        frontier, dist = [src], {src: 0}
        while frontier:
            nxt = []
            for node in frontier:
                for l in self.out_links(node):
                    if l.dst not in dist:
                        dist[l.dst] = dist[node] + 1
                        if l.dst == dst:
                            return dist[l.dst]
                        nxt.append(l.dst)
            frontier = nxt
        return -1


def _axis_node(axis: str, i: int) -> str:
    return f"{axis}{i}"


def ring_topology(axis: str, n: int, env: Optional[ModelEnv] = None) -> Topology:
    """Bidirectional ring of ICI links along one mesh axis.

    TPU ICI axes are wrapped tori, so every device has a +1 and a -1
    neighbor; both directions exist so reverse-rotation ring sketches
    ("ringr") price identically to the forward rotation.
    """
    alpha, beta = ici_link_params(env)
    links = []
    for i in range(max(1, n)):
        j = (i + 1) % max(1, n)
        if j == i:
            continue
        links.append(Link(_axis_node(axis, i), _axis_node(axis, j), "ici", alpha, beta))
        links.append(Link(_axis_node(axis, j), _axis_node(axis, i), "ici", alpha, beta))
    return Topology(tuple(links))


def host_topology(device: str = "d0", env: Optional[ModelEnv] = None) -> Topology:
    """PCIE staging links: device -> host (spill) and host -> device (fetch)."""
    alpha, beta = pcie_link_params(env)
    return Topology((
        Link(device, HOST_NODE, "pcie", alpha, beta),
        Link(HOST_NODE, device, "pcie", alpha, beta),
    ))


def mesh_topology(axes: Mapping[str, int], host: bool = True,
                  env: Optional[ModelEnv] = None) -> Topology:
    """Union of per-axis ICI rings plus the PCIE host link.

    ``axes`` mirrors the mesh signature the fingerprint already records:
    ordered (axis name -> extent).  Multi-axis meshes contribute one ring
    per axis; collectives synthesize along exactly one axis at a time, the
    same restriction the fixed engines observe.
    """
    topo = Topology()
    for axis, n in axes.items():
        if n > 1:
            topo = topo.merged(ring_topology(axis, n, env))
    if host:
        first = next(iter(axes), "d")
        topo = topo.merged(host_topology(_axis_node(first, 0), env))
    return topo
