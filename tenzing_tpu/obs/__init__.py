"""Unified telemetry: span/event tracing, metrics, progress reporting, export.

The observability layer the whole decision loop reports through (ISSUE 1):

* :mod:`tenzing_tpu.obs.tracer` — nested spans + instant events, thread-safe,
  near-zero overhead when disabled; every record is tagged with the control
  plane's rank so multi-host traces merge in one timeline.
* :mod:`tenzing_tpu.obs.context` — the cross-process trace context
  (``trace_id`` minted at serving ingress, carried through work-item
  envelopes and subprocess environments): while one is ambient, every
  span/event is stamped with it, so fleet bundles stitch per request.
* :mod:`tenzing_tpu.obs.metrics` — counters / gauges / histograms with
  percentile summaries; subsumes ``utils/counters.py`` (kept as a shim);
  plus the streaming metric-snapshot exporter long-lived serve processes
  publish their live state through.
* :mod:`tenzing_tpu.obs.progress` — human-readable progress lines that also
  flow into the tracer's event stream, replacing raw ``print()`` in library
  code (enforced by tests/test_no_print.py).
* :mod:`tenzing_tpu.obs.export` — JSONL (machine consumption) and Chrome
  trace-event JSON (load in Perfetto / chrome://tracing) sinks, and the
  cross-process trace stitcher (``python -m tenzing_tpu.obs.export``).
* :mod:`tenzing_tpu.obs.alerts` — the watchtower's alert engine
  (``python -m tenzing_tpu.obs.alerts check``): the declarative rule
  catalog (multi-window SLO burn, stale heartbeats, shed/queue/poison)
  evaluated over the fleet's status + snapshot documents, with a
  firing/resolved ledger CI gates on (docs/observability.md
  "Watchtower").

Everything here is stdlib-only so any module in the package can import it
without cycles.  See docs/observability.md for the end-to-end workflow.
"""

from tenzing_tpu.obs.context import TraceContext, new_trace
from tenzing_tpu.obs.export import (
    chrome_trace,
    read_jsonl,
    stitch,
    to_jsonl,
    write_chrome_trace,
    write_jsonl,
)
from tenzing_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsSnapshotWriter,
    SloConfig,
    get_metrics,
    latest_snapshots,
    set_metrics,
    snapshot_history,
)
from tenzing_tpu.obs.progress import ProgressReporter, get_reporter, set_reporter
from tenzing_tpu.obs.tracer import Event, Span, Tracer, configure, get_tracer, set_tracer

__all__ = [
    "Counter",
    "Event",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsSnapshotWriter",
    "ProgressReporter",
    "SloConfig",
    "Span",
    "TraceContext",
    "Tracer",
    "chrome_trace",
    "configure",
    "get_metrics",
    "get_reporter",
    "get_tracer",
    "latest_snapshots",
    "new_trace",
    "read_jsonl",
    "set_metrics",
    "set_reporter",
    "set_tracer",
    "snapshot_history",
    "stitch",
    "to_jsonl",
    "write_chrome_trace",
    "write_jsonl",
]
