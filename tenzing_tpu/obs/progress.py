"""ProgressReporter: human-readable search progress over the telemetry stream.

Library code MUST NOT ``print()`` (enforced by tests/test_no_print.py): every
human-facing progress line flows through the process-global reporter, which

1. writes the line to its stream (stderr by default — progress is diagnostics,
   never the machine-readable stdout the drivers own), and
2. mirrors it as a ``progress.<level>`` event into the global tracer, so an
   archived telemetry bundle contains the exact narrative a human saw
   interleaved with the spans that explain it.

The stream can be silenced (``ProgressReporter(stream=None)``) without losing
the event record — the telemetry bundle stays complete either way.
"""

from __future__ import annotations

import sys
from typing import Any, Optional, TextIO

from tenzing_tpu.obs.tracer import get_tracer


class ProgressReporter:
    """stderr narrative + tracer event stream, one call site (see module doc).

    ``stream=None`` silences the console copy; the default resolves to the
    CURRENT ``sys.stderr`` at emit time (so pytest capture and stream
    redirection keep working).
    """

    def __init__(self, stream: Optional[TextIO] = "stderr"):
        self._stream = stream

    def _emit(self, level: str, message: str, attrs: Any) -> None:
        get_tracer().event(f"progress.{level}", message=message, **attrs)
        stream = sys.stderr if self._stream == "stderr" else self._stream
        if stream is not None:
            try:
                stream.write(message.rstrip("\n") + "\n")
                stream.flush()
            except Exception:
                pass  # a closed/broken stream must not take down the search

    def info(self, message: str, **attrs: Any) -> None:
        self._emit("info", message, attrs)

    def warn(self, message: str, **attrs: Any) -> None:
        self._emit("warn", message, attrs)

    def error(self, message: str, **attrs: Any) -> None:
        self._emit("error", message, attrs)


_GLOBAL = ProgressReporter()


def get_reporter() -> ProgressReporter:
    """The process-global reporter every library call site uses."""
    return _GLOBAL


def set_reporter(reporter: ProgressReporter) -> ProgressReporter:
    """Swap the process-global reporter (tests); returns the previous one."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, reporter
    return prev
