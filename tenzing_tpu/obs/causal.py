"""Causal latency observatory: ``python -m tenzing_tpu.obs.causal``.

PR 12 made one ``trace_id`` span the whole fleet and PR 13 preserved
the exact worst requests behind a bad pct99 — but answering "where did
this request's time go" still meant reading stitched JSONL by hand (the
r02 phase read that steered PR 14 was literally that).  This module is
the automated read (docs/observability.md "Causal analysis"): rebuild
each trace's end-to-end timeline as an **ordered segment chain**,
attribute every microsecond to a named segment with an explicit
``unattributed`` residual, aggregate fleet-wide, and localize *which
segment moved* between two measurement documents.

**Segment taxonomy** (the chain a cold request walks end to end)::

    ingress -> fingerprint -> cache_probe -> store_walk -> [enqueue]
            -> queue_wait -> drain(search/compile/measure) -> merge

* ``ingress`` — the ``serve.query`` span before its first named child:
  admission, envelope parse, dispatch overhead.
* ``fingerprint`` / ``cache_probe`` / ``serialize`` — the resolver and
  transport sub-spans, verbatim.
* ``fast_path`` — a memoized exact hit's whole resolve.  The fast path
  emits its ``serve.query`` span post-hoc with ~0 duration (the real
  latency rides the ``resolve_us`` attribute — serve/resolver.py), so
  the analyzer synthesizes the interval from the attribute.
* ``store_walk`` — the remainder of ``serve.query`` after the first
  named child: store walk, near-tier surrogate pricing, the cold
  enqueue write.
* ``enqueue`` — the ``serve.enqueue`` event, a zero-duration chain
  marker: the instant the work item became durable.
* ``queue_wait`` — enqueue event to ``daemon.drain`` span start: the
  time the item sat in the work queue before any daemon claimed it.
  THE fleet-sizing signal (obs/alerts.py ``queue_backlog_burn``).
* ``search`` / ``compile`` / ``measure`` — the drain child's phases
  (solver, executor and benchmarker spans grouped by prefix).
* ``merge`` — ``serve.store.flush``: the store merge that makes the
  answer re-queryable; the chain's servable point.
* ``drain`` — the rest of the ``daemon.drain`` span (claim, checkpoint
  bookkeeping, subprocess spawn).
* ``unattributed`` — wall clock inside the trace's window that no
  record covers.  Always explicit: coverage = 1 - unattributed/window,
  and a low coverage number is itself a finding (telemetry gap).

Overlapping records are resolved by a priority sweep (specific beats
broad: a ``bench.benchmark`` microsecond is ``measure``, not ``drain``)
so every microsecond is attributed exactly once — segment sums never
double-count concurrent spans.

**Differential localization** (:func:`localize_phases` /
:func:`localize_segments`): given two SERVE_BENCH documents (or two
analyzed trace corpora), name the segment that moved.  A segment is
*moved* only past a deliberately coarse bar — pct99 ratio >=
``PHASE_REGRESSION_RATIO`` **and** an absolute delta above the measured
wake floor — because per-phase microsecond percentiles swing with host
noise far more than the paired ratios the bench gate consumes.  The
serve regression gate (obs/report.py ``check_serve_regression``) folds
the result into its reasons, so CI says "cache_probe regressed 3.1x"
instead of a bare pct99 number.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tenzing_tpu.utils.numeric import percentile

CAUSAL_VERSION = 1

# span name -> (segment, priority).  Higher priority wins a contested
# microsecond in the sweep; broad containers (daemon.drain) sit below
# their phase children, derived intervals (queue_wait, fast_path) sit
# between, and ingress/store_walk (derived from serve.query) at the
# bottom.
_PRIO_LEAF = 3       # named sub-spans: fingerprint, measure, merge, ...
_PRIO_DERIVED = 2    # queue_wait, fast_path
_PRIO_BROAD = 1      # drain remainder, ingress/store_walk remainder

SPAN_SEGMENTS: Dict[str, str] = {
    "serve.fingerprint": "fingerprint",
    "serve.cache_probe": "cache_probe",
    "serve.serialize": "serialize",
    "serve.store.flush": "merge",
    "serve.compaction": "merge",
    "learn.train": "search",
}

# prefix fallbacks for the drain child's solver/executor/benchmarker
# spans (one entry covers every mcts.iter etc. without enumerating)
PREFIX_SEGMENTS: List[Tuple[str, str]] = [
    ("mcts.", "search"),
    ("dfs.", "search"),
    ("learn.", "search"),
    ("executor.", "compile"),
    ("pipeline.", "compile"),
    ("fused.", "compile"),
    ("bench.", "measure"),
    ("attrib.", "measure"),
]

# localization bar (module docstring): phase percentiles are noisy
# microsecond quantities, so a phase is only *moved* past a 2x pct99
# ratio AND an absolute delta above the host's measured wake floor
# (fallback ABS floor when no host_noise block is recorded)
PHASE_REGRESSION_RATIO = 2.0
PHASE_ABS_FLOOR_US = 5.0
# percentiles over fewer than this many observations are not compared
MIN_PHASE_COUNT = 8


def _segment_of(name: str) -> Optional[str]:
    seg = SPAN_SEGMENTS.get(name)
    if seg is not None:
        return seg
    for prefix, s in PREFIX_SEGMENTS:
        if name.startswith(prefix):
            return s
    return None


def _trace_of(rec: Dict[str, Any]) -> Optional[str]:
    tid = (rec.get("attrs") or {}).get("trace_id")
    return tid if isinstance(tid, str) and tid else None


def group_by_trace(records: Iterable[Dict[str, Any]]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """Span/event records bucketed by the ``trace_id`` their attrs
    carry (obs/context.py stamps it while a context is ambient);
    records without one — process-local housekeeping — are dropped."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") not in ("span", "event"):
            continue
        tid = _trace_of(rec)
        if tid is None:
            continue
        out.setdefault(tid, []).append(rec)
    return out


def _intervals_for(recs: List[Dict[str, Any]]
                   ) -> Tuple[List[Tuple[float, float, str, int]],
                              List[Dict[str, Any]],
                              Dict[str, Any]]:
    """One trace's attributable intervals ``(start, end, segment,
    priority)``, its zero-duration chain markers, and the metadata
    mined along the way (tier/workload/query count/servable end)."""
    intervals: List[Tuple[float, float, str, int]] = []
    markers: List[Dict[str, Any]] = []
    queries: List[Dict[str, Any]] = []
    drains: List[Tuple[float, float]] = []
    enqueues: List[float] = []
    merge_ends: List[float] = []
    meta: Dict[str, Any] = {"tiers": [], "workloads": [], "queries": 0}

    spans = [r for r in recs if r.get("kind") == "span"]
    events = [r for r in recs if r.get("kind") == "event"]
    for r in spans:
        name = r.get("name", "")
        try:
            ts = float(r.get("ts_us", 0.0))
            dur = max(0.0, float(r.get("dur_us", 0.0)))
        except (TypeError, ValueError):
            continue
        attrs = r.get("attrs") or {}
        if name == "serve.query":
            meta["queries"] += 1
            tier = attrs.get("tier")
            if tier and tier not in meta["tiers"]:
                meta["tiers"].append(tier)
            wl = attrs.get("workload")
            if wl and wl not in meta["workloads"]:
                meta["workloads"].append(wl)
            if attrs.get("fast_path"):
                # post-hoc span: ~0 duration by design, the latency
                # rides resolve_us (serve/resolver.py) — synthesize
                # the interval it would have covered
                try:
                    res_us = max(0.0, float(attrs.get("resolve_us", 0.0)))
                except (TypeError, ValueError):
                    res_us = 0.0
                end = ts + dur
                intervals.append((end - res_us, end, "fast_path",
                                  _PRIO_DERIVED))
            else:
                queries.append({"start": ts, "end": ts + dur})
        elif name == "daemon.drain":
            drains.append((ts, ts + dur))
            intervals.append((ts, ts + dur, "drain", _PRIO_BROAD))
        else:
            seg = _segment_of(name)
            if seg is not None and dur > 0:
                intervals.append((ts, ts + dur, seg, _PRIO_LEAF))
                if seg == "merge":
                    merge_ends.append(ts + dur)
    for r in events:
        name = r.get("name", "")
        try:
            ts = float(r.get("ts_us", 0.0))
        except (TypeError, ValueError):
            continue
        if name == "serve.enqueue":
            enqueues.append(ts)
            markers.append({"segment": "enqueue", "ts_us": ts})
        elif name in ("serve.shed", "serve.queue.torn_item"):
            markers.append({"segment": name.split(".")[-1], "ts_us": ts})

    # serve.query remainder: before the first leaf child -> ingress,
    # after it -> store_walk (walk + near pricing + cold enqueue write)
    for q in queries:
        children = [iv for iv in intervals
                    if iv[3] == _PRIO_LEAF
                    and iv[0] >= q["start"] and iv[1] <= q["end"]]
        first = min((iv[0] for iv in children), default=q["end"])
        if first > q["start"]:
            intervals.append((q["start"], first, "ingress", _PRIO_BROAD))
        if q["end"] > first:
            intervals.append((first, q["end"], "store_walk", _PRIO_BROAD))

    # queue wait: enqueue event -> the first drain claiming it after
    drains.sort()
    for te in sorted(enqueues):
        td = next((s for s, _ in drains if s >= te), None)
        if td is not None and td > te:
            intervals.append((te, td, "queue_wait", _PRIO_DERIVED))
        elif td is None and drains:
            # drains exist but all started before the enqueue: the item
            # is still waiting — leave the tail unattributed (visible)
            pass
    meta["servable_end"] = max(merge_ends) if merge_ends else None
    meta["pending"] = bool(enqueues) and not drains
    return intervals, markers, meta


def _sweep(intervals: List[Tuple[float, float, str, int]],
           t0: float, t1: float) -> List[Dict[str, Any]]:
    """Priority sweep over ``[t0, t1]``: each elementary slice goes to
    the highest-priority covering interval (ties to the later start —
    the more specific context); uncovered slices become explicit
    ``unattributed`` entries.  Adjacent same-segment slices merge, so
    the result is the ordered chain."""
    cuts = {t0, t1}
    for s, e, _, _ in intervals:
        if e > t0 and s < t1:
            cuts.add(min(max(s, t0), t1))
            cuts.add(min(max(e, t0), t1))
    points = sorted(cuts)
    chain: List[Dict[str, Any]] = []
    for a, b in zip(points, points[1:]):
        if b <= a:
            continue
        best = None
        for s, e, seg, prio in intervals:
            if s <= a and e >= b:
                if best is None or (prio, s) > (best[1], best[2]):
                    best = (seg, prio, s)
        seg = best[0] if best is not None else "unattributed"
        if chain and chain[-1]["segment"] == seg:
            chain[-1]["end_us"] = b
        else:
            chain.append({"segment": seg, "start_us": a, "end_us": b})
    return chain


def analyze_trace(trace_id: str,
                  recs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One trace's causal result: the ordered chain (absolute times
    rebased to the trace start), per-segment totals, the explicit
    unattributed residual, and the queue-wait-vs-service split."""
    intervals, markers, meta = _intervals_for(recs)
    if not intervals:
        return {"trace_id": trace_id, "error": "no attributable records",
                "records": len(recs)}
    t0 = min(s for s, _, _, _ in intervals)
    # the window ends at the servable point (last store merge) when the
    # trace has one — a daemon's post-merge housekeeping is not request
    # latency — else at the last record
    t_end = max(e for _, e, _, _ in intervals)
    t1 = meta["servable_end"] if meta["servable_end"] else t_end
    t1 = max(t1, t0)
    chain = _sweep(intervals, t0, t1)
    segments: Dict[str, float] = {}
    for c in chain:
        c["dur_us"] = round(c["end_us"] - c["start_us"], 2)
        segments[c["segment"]] = segments.get(c["segment"], 0.0) \
            + c["dur_us"]
        c["start_us"] = round(c["start_us"] - t0, 2)
        c["end_us"] = round(c["end_us"] - t0, 2)
    for m in markers:
        m["ts_us"] = round(m["ts_us"] - t0, 2)
    window = round(t1 - t0, 2)
    unattr = round(segments.get("unattributed", 0.0), 2)
    queue_wait = round(segments.get("queue_wait", 0.0), 2)
    tiers = meta["tiers"]
    return {
        "trace_id": trace_id,
        "tier": "+".join(sorted(tiers)) if tiers else "?",
        "workloads": meta["workloads"],
        "queries": meta["queries"],
        "window_us": window,
        "servable": meta["servable_end"] is not None,
        "pending": meta["pending"],
        "chain": chain,
        "markers": sorted(markers, key=lambda m: m["ts_us"]),
        "segments_us": {k: round(v, 2) for k, v in sorted(segments.items())
                        if k != "unattributed"},
        "unattributed_us": unattr,
        "coverage": round(1.0 - (unattr / window), 4) if window else 1.0,
        "queue_wait_us": queue_wait,
        "service_us": round(window - unattr - queue_wait, 2),
    }


def analyze_records(records: Iterable[Dict[str, Any]],
                    trace_id: Optional[str] = None,
                    tenants: Optional[Dict[str, str]] = None,
                    ) -> Dict[str, Dict[str, Any]]:
    """Causal results for every trace in ``records`` (or just
    ``trace_id``); ``tenants`` optionally maps trace_id -> tenant for
    the per-tenant aggregation (span attrs do not carry it — the
    exemplar header's request record does)."""
    grouped = group_by_trace(records)
    if trace_id is not None:
        grouped = {trace_id: grouped.get(trace_id, [])}
    out: Dict[str, Dict[str, Any]] = {}
    for tid, recs in sorted(grouped.items()):
        res = analyze_trace(tid, recs)
        if tenants and tid in tenants:
            res["tenant"] = tenants[tid]
        out[tid] = res
    return out


def analyze_bundles(paths: List[str],
                    trace_id: Optional[str] = None
                    ) -> Dict[str, Dict[str, Any]]:
    """Causal results over telemetry JSONL bundles — raw tracer bundles
    (``--trace-out``), checkpoint trace files, or PR 13 exemplar
    bundles, whose line-0 header (``kind: "exemplar"``) supplies the
    tenant for the per-tenant breakdown."""
    from tenzing_tpu.obs.export import read_jsonl

    records: List[Dict[str, Any]] = []
    tenants: Dict[str, str] = {}
    for path in paths:
        for rec in read_jsonl(path):
            if rec.get("kind") == "exemplar":
                tid = rec.get("trace_id")
                tenant = ((rec.get("record") or {}).get("tenant"))
                if isinstance(tid, str) and isinstance(tenant, str):
                    tenants[tid] = tenant
                continue
            records.append(rec)
    return analyze_records(records, trace_id=trace_id,
                           tenants=tenants or None)


# -- fleet-wide aggregation --------------------------------------------------

def _dist(xs: List[float]) -> Dict[str, Any]:
    s = sorted(xs)
    return {"count": len(s),
            "p50_us": round(percentile(s, 50), 2),
            "p99_us": round(percentile(s, 99), 2),
            "sum_us": round(sum(s), 1)}


def aggregate(traces: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """The fleet-wide rollup (module docstring): per-tier and
    per-tenant segment breakdowns at p50/p99, the queue-wait-vs-service
    decomposition, and the "where the pct99 lives" ranking — segment
    shares summed over the tail traces (window >= the corpus p99)."""
    good = [t for t in traces.values() if "error" not in t]
    if not good:
        return {"n_traces": 0}

    def rollup(group: List[Dict[str, Any]]) -> Dict[str, Any]:
        segs: Dict[str, List[float]] = {}
        windows: List[float] = []
        unattr: List[float] = []
        for t in group:
            windows.append(t["window_us"])
            unattr.append(t["unattributed_us"])
            for seg, us in t["segments_us"].items():
                segs.setdefault(seg, []).append(us)
        return {
            "count": len(group),
            "window_us": _dist(windows),
            "unattributed_us": _dist(unattr),
            "segments_us": {seg: _dist(xs)
                            for seg, xs in sorted(segs.items())},
        }

    by_tier: Dict[str, List[Dict[str, Any]]] = {}
    by_tenant: Dict[str, List[Dict[str, Any]]] = {}
    for t in good:
        by_tier.setdefault(t.get("tier", "?"), []).append(t)
        by_tenant.setdefault(t.get("tenant", "?"), []).append(t)

    windows = sorted(t["window_us"] for t in good)
    p99_window = percentile(windows, 99)
    tail = [t for t in good if t["window_us"] >= p99_window] or \
        [max(good, key=lambda t: t["window_us"])]
    tail_segs: Dict[str, float] = {}
    for t in tail:
        for seg, us in t["segments_us"].items():
            tail_segs[seg] = tail_segs.get(seg, 0.0) + us
        tail_segs["unattributed"] = tail_segs.get("unattributed", 0.0) \
            + t["unattributed_us"]
    tail_total = sum(tail_segs.values()) or 1.0
    ranking = [{"segment": seg, "sum_us": round(us, 1),
                "share": round(us / tail_total, 4)}
               for seg, us in sorted(tail_segs.items(),
                                     key=lambda kv: -kv[1]) if us > 0]
    return {
        "n_traces": len(good),
        "by_tier": {k: rollup(v) for k, v in sorted(by_tier.items())},
        "by_tenant": {k: rollup(v) for k, v in sorted(by_tenant.items())},
        "decomposition": {
            "queue_wait_us": _dist([t["queue_wait_us"] for t in good]),
            "service_us": _dist([t["service_us"] for t in good]),
        },
        "pct99_window_us": round(p99_window, 2),
        "pct99_ranking": ranking,
    }


# -- differential localization -----------------------------------------------

def localize_segments(fresh: Dict[str, Dict[str, Any]],
                      base: Dict[str, Dict[str, Any]],
                      tol: float = 0.25,
                      floor_us: Optional[float] = None) -> Dict[str, Any]:
    """Which segment moved between two per-segment summary maps
    (``{segment: {"pct99_us", "count", ...}}``).  ``tol`` widens the
    coarse bar, never narrows it (module docstring); ``floor_us`` is
    the measured wake floor when available — deltas under the host's
    own noise floor are not movement."""
    ratio_bar = max(PHASE_REGRESSION_RATIO, 1.0 + tol)
    delta_floor = max(PHASE_ABS_FLOOR_US, floor_us or 0.0)
    moved: List[Dict[str, Any]] = []
    compared: List[Dict[str, Any]] = []
    skipped: List[str] = []
    for seg in sorted(set(fresh) | set(base)):
        f, b = fresh.get(seg) or {}, base.get(seg) or {}
        try:
            # SERVE_BENCH phase summaries say pct99_us, the causal
            # aggregate says p99_us — compare either
            fp99 = float(f.get("pct99_us", f.get("p99_us")))
            bp99 = float(b.get("pct99_us", b.get("p99_us")))
        except (TypeError, ValueError):
            skipped.append(seg)
            continue
        if min(int(f.get("count", 0)), int(b.get("count", 0))) \
                < MIN_PHASE_COUNT or bp99 <= 0:
            skipped.append(seg)
            continue
        ratio = fp99 / bp99
        entry = {"segment": seg, "fresh_pct99_us": round(fp99, 2),
                 "baseline_pct99_us": round(bp99, 2),
                 "ratio": round(ratio, 2)}
        compared.append(entry)
        if ratio >= ratio_bar and (fp99 - bp99) >= delta_floor:
            moved.append(dict(entry, moved=True))
    moved.sort(key=lambda m: -m["ratio"])
    return {"moved": moved, "compared": compared, "skipped": skipped,
            "ratio_bar": round(ratio_bar, 2),
            "delta_floor_us": round(delta_floor, 2)}


def localize_phases(fresh_doc: Dict[str, Any], base_doc: Dict[str, Any],
                    tol: float = 0.25) -> Dict[str, Any]:
    """:func:`localize_segments` over two SERVE_BENCH documents'
    per-phase samples (``segmented.phases_us``) — the automated version
    of the manual r02 phase read that steered PR 14.  The wake floor
    comes from the fresh document's ``host_noise`` block when it
    carries one."""
    def phases(doc):
        return (doc.get("segmented") or {}).get("phases_us") or {}

    floor = None
    hn = fresh_doc.get("host_noise")
    if isinstance(hn, dict):
        try:
            floor = float((hn.get("timer_wake_us") or {}).get("p99_us"))
        except (TypeError, ValueError):
            floor = None
    return localize_segments(phases(fresh_doc), phases(base_doc),
                             tol=tol, floor_us=floor)


def localize_aggregates(fresh_agg: Dict[str, Any],
                        base_agg: Dict[str, Any], tol: float = 0.25,
                        tier: str = "exact") -> Dict[str, Any]:
    """:func:`localize_segments` over two :func:`aggregate` results
    (two trace corpora), comparing one tier's segment p99s."""
    def segs(agg):
        return ((agg.get("by_tier") or {}).get(tier) or {}).get(
            "segments_us") or {}

    return localize_segments(segs(fresh_agg), segs(base_agg), tol=tol)


# -- CLI ---------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    import glob as _glob
    import os

    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.obs.causal",
        description="Rebuild per-request critical paths from telemetry "
                    "bundles and aggregate where the latency lives "
                    "(docs/observability.md 'Causal analysis').")
    ap.add_argument("bundles", nargs="*", metavar="GLOB",
                    help="telemetry JSONL bundles (tracer --trace-out, "
                         "checkpoint traces, exemplar bundles)")
    ap.add_argument("--trace-id", default=None,
                    help="analyze only this trace")
    ap.add_argument("--diff", nargs=2, default=None,
                    metavar=("FRESH", "BASELINE"),
                    help="localize which phase moved between two "
                         "SERVE_BENCH documents instead of analyzing "
                         "bundles; exit 1 when a segment moved")
    ap.add_argument("--tol", type=float, default=0.25,
                    help="--diff tolerance (serve-gate default 0.25)")
    ap.add_argument("--out", default=None,
                    help="write the JSON result here (default stdout)")
    args = ap.parse_args(argv)

    if args.diff:
        try:
            with open(args.diff[0]) as f:
                fresh = json.load(f)
            with open(args.diff[1]) as f:
                base = json.load(f)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"causal: {e}\n")
            return 2
        loc = localize_phases(fresh, base, tol=args.tol)
        doc: Dict[str, Any] = {"kind": "causal_diff",
                               "version": CAUSAL_VERSION,
                               "fresh": args.diff[0],
                               "baseline": args.diff[1], **loc}
        rc = 1 if loc["moved"] else 0
    else:
        paths: List[str] = []
        for pat in args.bundles:
            hits = sorted(_glob.glob(pat))
            paths.extend(hits if hits else
                         ([pat] if os.path.exists(pat) else []))
        if not paths:
            sys.stderr.write("causal: no bundles matched (and no --diff)\n")
            return 2
        traces = analyze_bundles(paths, trace_id=args.trace_id)
        doc = {"kind": "causal_analysis", "version": CAUSAL_VERSION,
               "bundles": paths, "n_traces": len(traces),
               "traces": traces, "aggregate": aggregate(traces)}
        rc = 0
    text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        sys.stderr.write(f"causal: {args.out}\n")
    else:
        sys.stdout.write(text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
