"""Timeline analysis: Gantt reconstruction, critical path, overlap
efficiency, dispatch overhead, roofline join.

Turns an :class:`~tenzing_tpu.obs.attrib.timeline.OpTimeline` (per-unit
durations, starts unassigned) plus the schedule's op list into the numbers
the driver stamps as the ``attrib`` block:

* **Gantt** — each unit's start is the max end of its happens-before
  predecessors.  The relation is the verifier's
  (:func:`tenzing_tpu.verify.soundness.happens_before_masks` — lane program
  order, host dispatch, the five sync ops' token semantics; deliberately no
  new HB logic here), so a unit's start already respects lane
  serialization, host-chain dispatch, and every sync edge.  ASAP
  scheduling under a closed precedence relation makes the model makespan
  equal to the **critical path** length.
* **overlap efficiency** = ``min(1, critical_path / measured)`` ∈ (0, 1]:
  the fraction of the HB-constrained ideal makespan the real fused program
  achieved.  1.0 means the hardware realized every overlap the schedule's
  ordering permits; small values mean ops that COULD overlap did not.
  Reported next to the raw triple (measured, sum-of-parts, critical path)
  so the ratio is re-derivable.
* **dispatch overhead** = ``max(0, sum_of_parts - measured)``: per-op
  stepped execution pays one dispatch + fence per op where the fused
  whole-schedule program pays one in total — the gap is the dispatch cost
  mega-kernelization removes (the MPK baseline number the ROADMAP item
  asks for), plus whatever overlap the schedule already hides.  For the
  NAIVE serial schedule the overlap term is ~zero, so its number is the
  clean per-workload dispatch overhead.
* **roofline join** — a workload :class:`~tenzing_tpu.bench.roofline.Cost`
  yields achieved fraction-of-peak at the measured makespan; per-op costs
  (when the caller can supply them) yield per-unit utilization.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tenzing_tpu.obs.attrib.timeline import OpTimeline


@dataclass
class Attribution:
    """The analysis verdict for one schedule (see module docstring)."""

    timeline: OpTimeline  # starts filled in
    sum_of_parts_us: float = 0.0
    critical_path_us: float = 0.0
    critical_path: List[str] = field(default_factory=list)
    measured_us: Optional[float] = None
    dispatch_overhead_us: float = 0.0
    overlap_efficiency: Optional[float] = None
    per_lane_busy_us: Dict[str, float] = field(default_factory=dict)
    utilization: Optional[Dict[str, float]] = None
    per_op_utilization: Optional[Dict[str, Dict[str, float]]] = None

    def to_json(self, with_timeline: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schedule": self.timeline.schedule,
            "source": self.timeline.source,
            "n_ops": self.timeline.n_ops,
            "n_timed": len(self.timeline.timed()),
            "sum_of_parts_us": round(self.sum_of_parts_us, 3),
            "critical_path_us": round(self.critical_path_us, 3),
            "measured_us": (round(self.measured_us, 3)
                            if self.measured_us is not None else None),
            "dispatch_overhead_us": round(self.dispatch_overhead_us, 3),
            "overlap_efficiency": (round(self.overlap_efficiency, 4)
                                   if self.overlap_efficiency is not None
                                   else None),
            "critical_path": list(self.critical_path),
            "per_lane_busy_us": {k: round(v, 3)
                                 for k, v in self.per_lane_busy_us.items()},
        }
        if self.utilization is not None:
            out["utilization"] = self.utilization
        if self.per_op_utilization is not None:
            out["per_op_utilization"] = self.per_op_utilization
        if with_timeline:
            out["timeline"] = [r.to_json() for r in self.timeline.records]
        return out


def lane_label(lane: Optional[int]) -> str:
    return "host" if lane is None else f"lane {lane}"


def analyze(ops, timeline: OpTimeline, measured_us: Optional[float] = None,
            cost=None, per_op_costs: Optional[Dict[str, Any]] = None,
            ) -> Attribution:
    """Fill the timeline's starts from the happens-before relation and
    compute the attribution verdict.

    ``ops`` is the schedule's op list (``order.vector()`` — positions must
    match ``timeline.records[*].positions``); ``measured_us`` the
    whole-program measured iteration time (the driver's final pct50);
    ``cost`` an optional workload :class:`~tenzing_tpu.bench.roofline.Cost`
    for the fraction-of-peak join; ``per_op_costs`` an optional
    ``unit name -> Cost`` map for per-unit utilization."""
    from tenzing_tpu.verify.soundness import happens_before_masks

    ops = list(ops)
    reach = happens_before_masks(ops)
    units = timeline.records
    # one bitmask per unit: which positions it covers, and which positions
    # happen-before any of its members (the union over members keeps a
    # grouped post→await unit ordered after everything any member needs)
    unit_bits: List[int] = []
    unit_reach: List[int] = []
    for rec in units:
        bits = 0
        mask = 0
        for p in rec.positions:
            bits |= 1 << p
            mask |= reach[p]
        unit_bits.append(bits)
        unit_reach.append(mask)

    ends: List[float] = []
    preds: List[int] = []
    for k, rec in enumerate(units):
        start, pred = 0.0, -1
        for j in range(k):
            if unit_reach[k] & unit_bits[j] and ends[j] > start:
                start, pred = ends[j], j
        rec.start_us = start
        ends.append(start + rec.dur_us)
        preds.append(pred)

    sum_parts = sum(r.dur_us for r in units)
    makespan = max(ends, default=0.0)
    # critical path: walk the argmax-predecessor chain back from the unit
    # that finishes last; sync units (zero duration) are kept out of the
    # reported names but still route the walk
    path: List[str] = []
    k = max(range(len(units)), key=lambda i: ends[i], default=None) \
        if units else None
    while k is not None and k >= 0:
        if units[k].dur_us > 0.0:
            path.append(units[k].name)
        k = preds[k]
    path.reverse()

    dispatch = 0.0
    efficiency: Optional[float] = None
    if measured_us is not None and measured_us > 0:
        dispatch = max(0.0, sum_parts - measured_us)
        efficiency = min(1.0, makespan / measured_us) if makespan > 0 else 1.0

    per_lane: Dict[str, float] = {}
    for rec in units:
        if rec.dur_us > 0:
            lbl = lane_label(rec.lane)
            per_lane[lbl] = per_lane.get(lbl, 0.0) + rec.dur_us

    util = None
    if cost is not None:
        secs = (measured_us if measured_us is not None else makespan) * 1e-6
        if secs > 0:
            util = {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in cost.utilization(secs).items()}
    per_op_util = None
    if per_op_costs:
        per_op_util = {}
        for rec in units:
            c = per_op_costs.get(rec.name)
            if c is not None and rec.dur_us > 0:
                per_op_util[rec.name] = {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in c.utilization(rec.dur_us * 1e-6).items()}

    return Attribution(
        timeline=timeline,
        sum_of_parts_us=sum_parts,
        critical_path_us=makespan,
        critical_path=path,
        measured_us=measured_us,
        dispatch_overhead_us=dispatch,
        overlap_efficiency=efficiency,
        per_lane_busy_us=per_lane,
        utilization=util,
        per_op_utilization=per_op_util,
    )
