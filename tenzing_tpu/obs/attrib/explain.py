"""Explain / diff: attribute a winner-vs-naive speedup to schedule decisions.

The paired verdict says the winner is N× faster; this module says *why*, by
diffing the two schedules (and, when available, their analyzed timelines)
along the axes the search actually decides:

* **lane placement** — which ops moved off the naive single lane, how many
  lanes the winner spreads over;
* **reordering** — inversions between the two orders over the ops they
  share (normalized Kendall-style), plus the biggest movers;
* **sync removal** — sync ops per kind present in naive but pruned (or
  added) in the winner;
* **menu choices** — ops whose chosen alternative differs (the
  ``base.suffix`` naming convention of ChoiceOp alternatives), and ops
  that exist on only one side (structural restructure, e.g. a transfer
  compound expanding differently);
* **timing decomposition** — the exact three-term split of the measured
  delta:  ``naive_measured − winner_measured =
  (naive_measured − naive_sum_parts) + (naive_sum_parts −
  winner_sum_parts) + (winner_sum_parts − winner_measured)`` — i.e. what
  the naive program already hid, what cheaper parts (kernel/engine menu
  picks) bought, and what overlap + dispatch removal bought.

The structural half works on bare schedules (no device, no timing) — the
recorded-corpus golden tests drive it that way; ``explain`` adds the
timing terms when both sides carry an :class:`Attribution`.

Perfetto: :func:`timeline_trace_events` renders an analyzed timeline as
per-lane tracks (one named thread row per lane + one for the host chain)
through the existing chrome-trace path (``obs/export.py`` —
``write_chrome_trace(..., extra_events=...)``), so attribution Gantts and
the PR-1 spans land in one grouped trace.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from tenzing_tpu.obs.attrib.analysis import Attribution, lane_label

# tid block for synthetic per-lane tracks in the chrome trace: far above any
# real dense thread index, stable across runs; the host-chain track of a
# block sits at ``tid_base - 1``
LANE_TID_BASE = 1000


def _sync_counts(ops) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for op in ops:
        if getattr(op, "is_sync", lambda: False)():
            k = getattr(op, "KIND", "sync")
            out[k] = out.get(k, 0) + 1
    return out


def _lane_map(ops) -> Dict[str, Optional[int]]:
    """op name -> lane id (None = host) for the non-sync ops."""
    from tenzing_tpu.core.operation import BoundDeviceOp

    out: Dict[str, Optional[int]] = {}
    for op in ops:
        if getattr(op, "is_sync", lambda: False)():
            continue
        out[op.name()] = op.lane().id if isinstance(op, BoundDeviceOp) else None
    return out


def _menu_base(name: str) -> str:
    """'unpack_x.pallas' -> 'unpack_x'; names without a menu suffix map to
    themselves (the ChoiceOp alternative naming convention)."""
    return name.rsplit(".", 1)[0] if "." in name else name


def diff_schedules(naive_ops, winner_ops) -> Dict[str, Any]:
    """Structure-only decision diff (no timing needed — see module doc)."""
    naive_ops, winner_ops = list(naive_ops), list(winner_ops)
    n_lanes = _lane_map(naive_ops)
    w_lanes = _lane_map(winner_ops)

    # lane placement
    n_used = sorted({l for l in n_lanes.values() if l is not None})
    w_used = sorted({l for l in w_lanes.values() if l is not None})
    moved = sorted(name for name in set(n_lanes) & set(w_lanes)
                   if n_lanes[name] != w_lanes[name])

    # reordering over shared names
    shared = [n for n in n_lanes if n in w_lanes]
    n_pos = {n: i for i, n in enumerate(
        op.name() for op in naive_ops
        if not getattr(op, "is_sync", lambda: False)())}
    w_pos = {n: i for i, n in enumerate(
        op.name() for op in winner_ops
        if not getattr(op, "is_sync", lambda: False)())}
    inversions = 0
    for i, a in enumerate(shared):
        for b in shared[i + 1:]:
            if (n_pos[a] - n_pos[b]) * (w_pos[a] - w_pos[b]) < 0:
                inversions += 1
    pairs = len(shared) * (len(shared) - 1) // 2
    movers = sorted(shared, key=lambda n: -abs(n_pos[n] - w_pos[n]))[:8]
    movers = [n for n in movers if n_pos[n] != w_pos[n]]

    # sync vocabulary
    ns, ws = _sync_counts(naive_ops), _sync_counts(winner_ops)
    removed = {k: ns.get(k, 0) - ws.get(k, 0)
               for k in set(ns) | set(ws)
               if ns.get(k, 0) != ws.get(k, 0)}

    # menu choices: same base, different chosen suffix
    n_by_base = {_menu_base(n): n for n in n_lanes}
    w_by_base = {_menu_base(n): n for n in w_lanes}
    changed = {b: {"naive": n_by_base[b], "winner": w_by_base[b]}
               for b in sorted(set(n_by_base) & set(w_by_base))
               if n_by_base[b] != w_by_base[b]}
    only_naive = sorted(b for b in n_by_base if b not in w_by_base)
    only_winner = sorted(b for b in w_by_base if b not in n_by_base)

    return {
        "lanes": {
            "naive_lanes": n_used,
            "winner_lanes": w_used,
            "ops_moved": moved,
            "n_ops_moved": len(moved),
        },
        "reorder": {
            "shared_ops": len(shared),
            "inversions": inversions,
            "normalized": round(inversions / pairs, 4) if pairs else 0.0,
            "top_movers": movers,
        },
        "sync": {
            "naive": ns,
            "winner": ws,
            "delta": removed,  # positive = removed by the winner
        },
        "menu": {
            "changed_choices": changed,
            "only_in_naive": only_naive,
            "only_in_winner": only_winner,
        },
    }


def explain(naive_ops, winner_ops,
            naive_attrib: Optional[Attribution] = None,
            winner_attrib: Optional[Attribution] = None) -> Dict[str, Any]:
    """The full explain document: the structural decision diff plus (when
    both analyses are given) the three-term timing decomposition."""
    doc: Dict[str, Any] = {"decisions": diff_schedules(naive_ops, winner_ops)}
    if naive_attrib is not None and winner_attrib is not None and \
            naive_attrib.measured_us and winner_attrib.measured_us:
        nm, wm = naive_attrib.measured_us, winner_attrib.measured_us
        ns, wsum = naive_attrib.sum_of_parts_us, winner_attrib.sum_of_parts_us
        doc["timing"] = {
            "naive_measured_us": round(nm, 3),
            "winner_measured_us": round(wm, 3),
            "speedup": round(nm / wm, 4) if wm > 0 else None,
            # exact decomposition: the three terms sum to naive - winner
            "delta_us": round(nm - wm, 3),
            "naive_hidden_us": round(nm - ns, 3),
            "faster_parts_us": round(ns - wsum, 3),
            "winner_hidden_us": round(wsum - wm, 3),
            "naive_overlap_efficiency": naive_attrib.overlap_efficiency,
            "winner_overlap_efficiency": winner_attrib.overlap_efficiency,
            "naive_critical_path_us": round(naive_attrib.critical_path_us, 3),
            "winner_critical_path_us": round(winner_attrib.critical_path_us, 3),
            "dispatch_overhead_us": round(
                winner_attrib.dispatch_overhead_us, 3),
        }
    return doc


def write_explain(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)


def timeline_trace_events(attrib: Attribution, pid: int = 0,
                          t0_us: float = 0.0, label: str = "attrib",
                          tid_base: int = LANE_TID_BASE,
                          ) -> List[Dict[str, Any]]:
    """Chrome trace-event dicts rendering an analyzed timeline as per-lane
    tracks: complete events (``ph: "X"``) on one synthetic tid per lane
    (+ one for the host chain, at ``tid_base - 1``), each track named
    ``<label>/lane N`` via ``thread_name`` metadata.  Feed to
    ``obs.export.write_chrome_trace(..., extra_events=...)`` — the PR-1
    spans and these Gantt tracks then render as one grouped trace per
    rank.  Give each timeline its own ``tid_base`` block (winner vs naive)
    so their lane tracks don't collide."""
    events: List[Dict[str, Any]] = []
    tids: Dict[int, str] = {}
    host_tid = tid_base - 1
    for rec in attrib.timeline.records:
        if rec.dur_us <= 0:
            continue
        tid = host_tid if rec.lane is None else tid_base + rec.lane
        tids[tid] = f"{label}/{lane_label(rec.lane)}"
        events.append({
            "name": rec.name,
            "cat": "attrib",
            "ph": "X",
            "ts": t0_us + rec.start_us,
            "dur": rec.dur_us,
            "pid": pid,
            "tid": tid,
            "args": {"kind": rec.kind, "positions": list(rec.positions),
                     "schedule": attrib.timeline.schedule},
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": nm}} for tid, nm in sorted(tids.items())]
    return meta + events
