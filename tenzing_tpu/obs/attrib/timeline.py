"""Per-op timelines: the attribution profiler's measurement substrate.

An :class:`OpTimeline` is the per-op record set every attribution question
reduces to — (op, lane, start, duration) per timed unit — produced by the
**timed execution mode** (:func:`stepped_timeline` over
``TraceExecutor.op_stepped``): each op of a schedule runs as its own jitted
sub-program against the buffer state the previous steps produced, timed
with the same fetch-fenced discipline the benchmarker uses (median of
``repeats`` walls minus the calibrated trivial-fetch overhead).

What stepped durations mean — and what they do not:

* every step is **serial** (a step completes before the next starts), so
  the durations are overlap-free "sum of parts" components; the *starts*
  on the records are NOT measured — they are reconstructed by the analysis
  layer (analysis.py) from the happens-before relation, which is exactly
  what makes the critical-path / overlap-efficiency numbers attributable
  to schedule decisions rather than to measurement accidents;
* each step pays one dispatch + fence round trip, and its fence is a full
  reduction over the op's written buffers — both are part of the measured
  step cost.  The stepped sum therefore *over*-counts what the ops cost
  inside the one fused whole-schedule program, which is the point: the gap
  between the stepped sum and the measured whole-program time IS the
  dispatch overhead mega-kernelization removes (the MPK baseline number,
  ROADMAP "Mega-kernelize").
* sync ops are zero-duration records (token bookkeeping compiles to
  nothing timeable alone); split-kernel post→await groups are one record
  covering all member positions (the wait closure cannot cross a program
  boundary — see ``TraceExecutor.op_stepped``).

The xplane capture path (xplane.py) is the multi-chip fallback; it
attributes by kernel name rather than by schedule position.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer

# floor (µs) for a timed step the overhead subtraction pushed to <= 0: keeps
# every downstream ratio (overlap efficiency, per-lane shares) well-defined
# without inventing measurable time
MIN_DUR_US = 1e-3


@dataclass
class OpRecord:
    """One timed unit of a schedule: a single op, or a split-kernel
    post→await group (``positions`` then spans every member)."""

    name: str
    desc: str
    kind: str  # "device" | "host" | "sync"
    lane: Optional[int]  # lane id for device ops, None = host chain
    positions: Tuple[int, ...]
    dur_us: float = 0.0
    start_us: float = 0.0  # reconstructed by analysis.py, 0 until assigned

    @property
    def end_us(self) -> float:
        return self.start_us + self.dur_us

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "desc": self.desc,
            "kind": self.kind,
            "lane": self.lane,
            "positions": list(self.positions),
            "start_us": round(self.start_us, 4),
            "dur_us": round(self.dur_us, 4),
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "OpRecord":
        return cls(name=j["name"], desc=j.get("desc", j["name"]),
                   kind=j["kind"], lane=j.get("lane"),
                   positions=tuple(j["positions"]),
                   dur_us=float(j.get("dur_us", 0.0)),
                   start_us=float(j.get("start_us", 0.0)))


@dataclass
class OpTimeline:
    """The (op, lane, start, duration) record set for one schedule."""

    records: List[OpRecord] = field(default_factory=list)
    schedule: str = ""  # schedule_id digest (bench/benchmarker.py)
    source: str = "stepped"  # "stepped" | "xplane" | "synthetic"
    n_ops: int = 0
    repeats: int = 0
    fetch_overhead_us: float = 0.0

    def timed(self) -> List[OpRecord]:
        """The non-sync records (the units that carry measured duration)."""
        return [r for r in self.records if r.kind != "sync"]

    def to_json(self) -> Dict[str, Any]:
        return {
            "schedule": self.schedule,
            "source": self.source,
            "n_ops": self.n_ops,
            "repeats": self.repeats,
            "fetch_overhead_us": round(self.fetch_overhead_us, 4),
            "records": [r.to_json() for r in self.records],
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "OpTimeline":
        return cls(records=[OpRecord.from_json(r) for r in j["records"]],
                   schedule=j.get("schedule", ""),
                   source=j.get("source", "stepped"),
                   n_ops=int(j.get("n_ops", 0)),
                   repeats=int(j.get("repeats", 0)),
                   fetch_overhead_us=float(j.get("fetch_overhead_us", 0.0)))


def _record_meta(ops, positions) -> Tuple[str, str, str, Optional[int]]:
    """(name, desc, kind, lane) of the unit covering ``positions``."""
    from tenzing_tpu.core.operation import BoundDeviceOp

    members = [ops[p] for p in positions]
    non_sync = [o for o in members
                if not getattr(o, "is_sync", lambda: False)()]
    if not non_sync:
        op = members[0]
        lanes = op.lanes() if hasattr(op, "lanes") else []
        return op.desc(), op.desc(), "sync", (lanes[0].id if lanes else None)
    name = "+".join(o.name() for o in non_sync)
    desc = non_sync[0].desc() if len(non_sync) == 1 else name
    dev = next((o for o in non_sync if isinstance(o, BoundDeviceOp)), None)
    if dev is not None:
        return name, desc, "device", dev.lane().id
    return name, desc, "host", None


def fetch_overhead_us() -> float:
    """Median wall of a trivial compiled fetch (dispatch + tunnel RTT), in
    microseconds — the same calibration the EmpiricalBenchmarker subtracts
    per measurement, re-derived here so the profiler needs no benchmarker."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    jax.device_get(f(x))  # compile
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        jax.device_get(f(x))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2] * 1e6


def stepped_timeline(executor, order, repeats: int = 7) -> OpTimeline:
    """Time every op of ``order`` through the executor's per-op stepped
    mode (``TraceExecutor.op_stepped``) and return the
    :class:`OpTimeline` (starts unassigned — run analysis.py over it).

    Each step is compiled+warmed once (excluded), then timed ``repeats``
    times against the SAME input buffers; the recorded duration is the
    median wall minus the calibrated fetch overhead, floored at
    ``MIN_DUR_US``.  Buffer state advances once per step, so later ops see
    exactly the values the schedule produces.
    """
    import jax

    from tenzing_tpu.bench.benchmarker import schedule_id

    tr = get_tracer()
    sid = schedule_id(order)
    with tr.span("attrib.profile", schedule=sid, repeats=repeats) as sp:
        steps = executor.op_stepped(order)
        ops = order.vector()
        overhead_us = fetch_overhead_us()
        bufs = executor.init_bufs
        records: List[OpRecord] = []
        n_timed = 0
        for positions, fn in steps:
            name, desc, kind, lane = _record_meta(ops, positions)
            if fn is None:
                records.append(OpRecord(name=name, desc=desc, kind=kind,
                                        lane=lane, positions=positions))
                continue

            def run(b=bufs, fn=fn):
                fence, out = fn(b)
                jax.device_get(fence)
                # host-space writes don't feed the fence; block on the rest
                jax.block_until_ready(out)
                return out

            with tr.span("attrib.step", unit=name):
                out = run()  # compile + warm, excluded from timing
                walls = []
                for _ in range(max(1, repeats)):
                    t0 = time.perf_counter()
                    run()
                    walls.append(time.perf_counter() - t0)
                walls.sort()
                dur_us = max(walls[len(walls) // 2] * 1e6 - overhead_us,
                             MIN_DUR_US)
            records.append(OpRecord(name=name, desc=desc, kind=kind,
                                    lane=lane, positions=positions,
                                    dur_us=dur_us))
            n_timed += 1
            bufs = out
        sp.set("n_timed", n_timed)
        get_metrics().counter("attrib.profiles").inc()
        get_metrics().counter("attrib.steps").inc(n_timed)
    return OpTimeline(records=records, schedule=sid, source="stepped",
                      n_ops=len(ops), repeats=repeats,
                      fetch_overhead_us=overhead_us)
