"""Schedule attribution profiler: per-op timelines, critical-path and
overlap analytics, winner-vs-naive explanation (ISSUE 6).

PR 1's telemetry answers "what happened when" at subsystem granularity;
this package answers **why a schedule is fast or slow, per op and per
decision**:

* :mod:`~tenzing_tpu.obs.attrib.timeline` — the timed execution mode:
  per-op stepped sub-programs over ``TraceExecutor.op_stepped`` produce an
  :class:`OpTimeline` of (op, lane, start, duration) records;
* :mod:`~tenzing_tpu.obs.attrib.analysis` — Gantt reconstruction on the
  verifier's happens-before relation, critical path, overlap efficiency,
  dispatch overhead (the MPK baseline number), roofline join;
* :mod:`~tenzing_tpu.obs.attrib.explain` — winner-vs-naive decision diff
  (lanes / reorder / sync removal / menu choices), the three-term timing
  decomposition, ``explain.json``, per-lane Perfetto tracks;
* :mod:`~tenzing_tpu.obs.attrib.xplane` — the device-plane jax.profiler
  capture + concurrency analysis (absorbed from ``utils/profiling.py``,
  which remains as a deprecation shim), the multi-chip fallback.

Driver surface: ``bench.py --profile-winner`` stamps the ``attrib`` block
into the driver JSON; ``python -m tenzing_tpu.obs.report`` mines corpora
and runs the regression check.  See docs/observability.md "Attribution".

Deliberately NOT imported from ``tenzing_tpu.obs`` eagerly: ``obs`` stays
stdlib-only importable; everything jax-touching here is lazy.
"""

from tenzing_tpu.obs.attrib.analysis import Attribution, analyze, lane_label
from tenzing_tpu.obs.attrib.explain import (
    diff_schedules,
    explain,
    timeline_trace_events,
    write_explain,
)
from tenzing_tpu.obs.attrib.timeline import (
    OpRecord,
    OpTimeline,
    fetch_overhead_us,
    stepped_timeline,
)
from tenzing_tpu.obs.attrib.xplane import (
    analyze_trace,
    capture_trace,
    merge_intervals,
)

__all__ = [
    "Attribution",
    "OpRecord",
    "OpTimeline",
    "analyze",
    "analyze_trace",
    "capture_trace",
    "diff_schedules",
    "explain",
    "fetch_overhead_us",
    "lane_label",
    "merge_intervals",
    "stepped_timeline",
    "timeline_trace_events",
    "write_explain",
]
