"""Device-side xplane profiling: jax.profiler traces + transfer/compute
concurrency analysis — the attribution profiler's fallback timing source.

The primary timing source for attribution is the per-op stepped mode
(:mod:`tenzing_tpu.obs.attrib.timeline` over ``TraceExecutor.op_stepped``),
which is single-chip only.  This module is the complement that works on any
platform the profiler can attach to: capture an ``xplane`` trace of a
schedule running under the executor, and parse it programmatically to
measure how much wall time has a transfer (DMA/copy) event concurrent with
device compute — the quantity a searched overlap schedule exists to create.

History: this code began life as ``utils/profiling.py`` (SURVEY.md §5 maps
the reference's host-side phase counters — its ``counters.hpp``, whose
in-repo analog is the ``utils/counters.py`` shim over ``obs/metrics`` — to
JAX profiler traces on TPU).  ``utils/profiling.py`` is now a deprecation
shim re-exporting this module.  The archived on-TPU evidence lives in
``experiments/PROFILE_OVERLAP.json`` (driver:
``experiments/profile_overlap.py``, which also documents the naive-vs-
overlap halo comparison) and ``experiments/PROFILE_WINNER.json``
(``experiments/profile_winner.py``, the winner's per-op-name breakdown).

The analysis is keyword-based over the device planes' event names: transfer
events (copy/dma/transfer/send/recv/infeed/outfeed) vs compute events
(fusion/slice/convert/...), with outer control events (while/loop) excluded —
they span the whole program and would make every DMA look concurrent.
Intervals are coalesced before intersection so each nanosecond counts once.
"""

from __future__ import annotations

import glob
from pathlib import Path
from typing import Dict, List, Sequence as Seq, Tuple

TRANSFER_KEYWORDS = ("copy", "dma", "transfer", "infeed", "outfeed", "send",
                     "recv", "all-reduce", "reduce-scatter", "all-gather",
                     "all-to-all", "collective", "permute", "rdma")
COMPUTE_KEYWORDS = ("fusion", "dynamic", "slice", "pad", "convert", "reshape",
                    "add", "concatenate", "custom-call", "custom_call", "dot",
                    "matmul", "gelu", "broadcast", "select", "iota",
                    "transpose", "mosaic")
# outer control events span the whole program and would make every DMA look
# concurrent — they are neither transfer nor compute nor "unclassified"
CONTROL_KEYWORDS = ("while", "loop", "condition", "body", "call", "region")


def capture_trace(executor, order, out_dir, iters: int = 3) -> Tuple[Path, float]:
    """Run ``order`` ``iters`` times under ``jax.profiler.trace`` and return
    (trace directory, wall seconds).  The schedule is compiled and warmed
    first so the trace holds steady-state execution, not compilation."""
    import time

    import jax

    run_n = executor.prepare_n(order)
    run_n(1)  # compile + warm outside the trace
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(str(out_dir)):
        run_n(iters)
    return out_dir, time.perf_counter() - t0


def merge_intervals(ivs: Seq[Tuple[int, int]]) -> List[List[int]]:
    """Coalesce intervals so busy time and intersections count each
    nanosecond once."""
    out: List[List[int]] = []
    for a, b in sorted(ivs):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def analyze_trace(trace_dir) -> Dict[str, float]:
    """Transfer-vs-compute concurrency on the device planes of the newest
    xplane file under ``trace_dir`` (see module docstring for the method)."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(str(Path(trace_dir) / "**" / "*.xplane.pb"),
                             recursive=True))
    if not paths:
        return {"error": f"no xplane under {trace_dir}"}
    data = ProfileData.from_file(paths[-1])
    xfers: List[Tuple[int, int]] = []
    computes: List[Tuple[int, int]] = []
    unclassified: List[Tuple[int, int]] = []
    for plane in data.planes:
        pname = plane.name.lower()
        if not ("tpu" in pname or "device" in pname or "xla" in pname):
            continue
        for line in plane.lines:
            for ev in line.events:
                nm = (ev.name or "").lower()
                iv = (ev.start_ns, ev.end_ns)
                if iv[1] <= iv[0]:
                    continue
                if any(k in nm for k in TRANSFER_KEYWORDS):
                    xfers.append(iv)
                elif any(k in nm for k in COMPUTE_KEYWORDS):
                    computes.append(iv)
                elif not any(k in nm for k in CONTROL_KEYWORDS):
                    # neither transfer, compute, nor outer control: report it
                    # so silent misclassification is visible (ADVICE r3)
                    unclassified.append(iv)

    def total(ivs):
        return sum(b - a for a, b in merge_intervals(ivs))

    overlap_ns = 0
    computes_merged = merge_intervals(computes)
    for a, b in merge_intervals(xfers):
        for c, d in computes_merged:
            if c >= b:
                break
            lo, hi = max(a, c), min(b, d)
            if hi > lo:
                overlap_ns += hi - lo
    return {
        "xplane": paths[-1],
        "n_transfer_events": len(xfers),
        "n_compute_events": len(computes),
        "n_unclassified_events": len(unclassified),
        "transfer_busy_ms": total(xfers) / 1e6,
        "compute_busy_ms": total(computes) / 1e6,
        "unclassified_busy_ms": total(unclassified) / 1e6,
        "transfer_concurrent_with_compute_ms": overlap_ns / 1e6,
    }
