"""Metrics registry: counters, gauges, histograms with percentile summaries.

The quantitative half of the telemetry subsystem: where the tracer answers
"what happened when", the registry answers "how much / how often / how
slow".  One process-global registry (:func:`get_metrics`) aggregates across
the whole decision loop — solver phase timings (via the ``utils/counters.py``
shim), benchmark cache hit rates, measurement counts — and serializes to one
JSON document (``bench.py --metrics-json``).

Histogram summaries use the same nearest-rank percentile convention as
``BenchResult`` (utils/numeric.py::percentile — a stdlib-only module, so the
import stays cycle-free) and retain raw observations up to a cap so archived
metrics can be re-derived offline without hot loops growing memory unbounded.

The **streaming exporter** (:class:`MetricsSnapshotWriter`) is the
fleet-facing half (docs/observability.md "Fleet telemetry plane"): a
long-lived process (``serve listen``, the drain daemon) periodically
writes an atomic **metric-snapshot document** into a bounded ring of
files next to its ``status-<owner>.json`` — the whole registry
serialized non-blocking, the tracer's retention/drop tallies, and an
**SLO block** (:class:`SloConfig`: the exact-tier pct99 vs a configured
target and vs the committed ``SERVE_BENCH`` baseline, with the burn
direction).  ``obs/report.py --follow`` tails these documents.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from tenzing_tpu.utils.numeric import percentile


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Distribution of observations with a percentile summary.

    Aggregates (count/sum/min/max) are exact and O(1) per observation; raw
    values are retained only up to ``max_raw`` for the percentile summary —
    a hot loop observing per-node timings (DFS enumeration) cannot grow
    memory without bound.  A truncated summary carries ``raw_retained`` and
    ``truncated: true`` so downstream tooling (e.g. the report CLI,
    obs/report.py) labels the percentiles prefix-only instead of silently
    treating them as full-series statistics.

    ``window=True`` retains the most RECENT ``max_raw`` observations
    instead of the first (a deque ring): the serving-latency series a
    live SLO block reads must reflect current traffic — first-N
    retention would freeze the pct99 at whatever the process saw before
    the cap filled, hiding every regression after warm-up.  Windowed
    summaries carry ``window: true`` (+ ``raw_retained``) instead of
    ``truncated``."""

    __slots__ = ("name", "_lock", "_values", "_count", "_sum", "_min",
                 "_max", "_max_raw", "_window")

    def __init__(self, name: str, max_raw: int = 65536,
                 window: bool = False):
        self.name = name
        self._lock = threading.Lock()
        self._window = bool(window)
        self._max_raw = max(1, max_raw)
        self._values = (deque(maxlen=self._max_raw) if self._window
                        else [])  # type: ignore[var-annotated]
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value
            if self._window or len(self._values) < self._max_raw:
                self._values.append(value)  # deque evicts oldest itself

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return self._sum

    def values(self) -> List[float]:
        """The retained raw observations (all of them below ``max_raw``)."""
        with self._lock:
            return list(self._values)

    def summary(self, block: bool = True) -> Dict[str, float]:
        """count/sum/min/max/mean + nearest-rank p50/p90/p99.

        ``block=False`` is the async-signal-safe read (bench.py's trap-path
        ``write_telemetry``): if the instrument lock cannot be acquired —
        the interrupted thread may hold it mid-``observe`` — the summary is
        computed from a GIL-atomic copy of the fields instead of blocking
        on a lock that will never be released."""
        acquired = self._lock.acquire(blocking=block)
        try:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
            xs = sorted(self._values)
        finally:
            if acquired:
                self._lock.release()
        if count == 0 or not xs:
            # xs can be empty at count > 0 only on a torn non-blocking read
            # (observe() bumps count before appending); report the exact
            # aggregates without percentiles rather than crash in the trap
            return {"count": count, "sum": total}
        out = {
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "mean": total / count,
            "p50": percentile(xs, 50),
            "p90": percentile(xs, 90),
            "p99": percentile(xs, 99),
        }
        if len(xs) < count:
            # the retained-raw cap bounded the series: the percentiles
            # cover only ``raw_retained`` of ``count`` observations —
            # the FIRST raw_retained for plain histograms (``truncated``,
            # labeled "prefix-only" by the report CLI) or the most
            # RECENT for windowed ones (``window``, labeled
            # "recent-window").  Explicit markers so readers never have
            # to compare count vs raw_retained themselves.
            out["raw_retained"] = len(xs)
            if self._window:
                out["window"] = True
            else:
                out["truncated"] = True
        return out


class MetricsRegistry:
    """Get-or-create namespace of instruments; serializes to one JSON doc."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            inst = self._counters.get(name)
            if inst is None:
                inst = self._counters[name] = Counter(name)
            return inst

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            inst = self._gauges.get(name)
            if inst is None:
                inst = self._gauges[name] = Gauge(name)
            return inst

    def histogram(self, name: str, max_raw: Optional[int] = None,
                  window: bool = False) -> Histogram:
        """Get-or-create; ``max_raw`` / ``window`` shape the raw-series
        retention and apply only at creation (the first caller of a
        name decides — a long-lived serve loop passes a small windowed
        cap for its latency series so live percentiles track current
        traffic, docs/observability.md)."""
        with self._lock:
            inst = self._histograms.get(name)
            if inst is None:
                kwargs: Dict[str, Any] = {"window": window}
                if max_raw is not None:
                    kwargs["max_raw"] = max_raw
                inst = self._histograms[name] = Histogram(name, **kwargs)
            return inst

    def histograms(self) -> Dict[str, Histogram]:
        """Snapshot of the registered histograms (name -> instrument)."""
        with self._lock:
            return dict(self._histograms)

    @contextmanager
    def timer(self, name: str):
        """Time a block into histogram ``name`` (seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).observe(time.perf_counter() - t0)

    def to_json(self, block: bool = True) -> Dict[str, Any]:
        """Serialize every instrument.  ``block=False`` is the
        async-signal-safe variant (the trap path, bench.py
        ``write_telemetry``): registry and per-histogram locks are taken
        non-blocking with GIL-atomic dict/list copies as the fallback, so a
        signal handler can archive metrics even while the interrupted
        thread holds an instrument lock."""
        acquired = self._lock.acquire(blocking=block)
        try:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        finally:
            if acquired:
                self._lock.release()
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.summary(block=block)
                           for n, h in sorted(histograms.items())},
        }

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_GLOBAL = MetricsRegistry()


def get_metrics() -> MetricsRegistry:
    """The process-global registry (always live — recording into it is cheap
    and reading it is opt-in, so there is no enabled flag to thread around)."""
    return _GLOBAL


def set_metrics(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous one."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, registry
    return prev


# -- streaming snapshot exporter (the fleet telemetry plane) ----------------

SNAPSHOT_VERSION = 1


@dataclass
class SloConfig:
    """What "healthy" means for one latency series (module docstring).

    ``target_us`` is the operator's objective (the ROADMAP's
    tens-of-µs exact-tier goal); ``baseline_pct99_us`` anchors the burn
    direction — normally read from the committed ``SERVE_BENCH_r*.json``
    family via :func:`baseline_pct99_from`."""

    target_us: Optional[float] = None
    baseline_pct99_us: Optional[float] = None
    histogram: str = "serve.resolve_us.exact"
    # beyond this relative drift from the baseline the burn direction
    # stops reading "flat" — the same 5% the regression gate defaults to
    drift_tol: float = 0.05

    def block(self, registry: MetricsRegistry) -> Dict[str, Any]:
        """The SLO block one snapshot carries: current pct99 of the
        configured histogram vs target and baseline."""
        hist = registry.histograms().get(self.histogram)
        summary = hist.summary(block=False) if hist is not None else {}
        pct99 = summary.get("p99")
        out: Dict[str, Any] = {
            "histogram": self.histogram,
            "count": summary.get("count", 0),
            "pct99_us": pct99,
            "target_us": self.target_us,
            "baseline_pct99_us": self.baseline_pct99_us,
        }
        if pct99 is not None and self.target_us:
            out["within_target"] = bool(pct99 <= self.target_us)
        if pct99 is not None and self.baseline_pct99_us:
            ratio = pct99 / self.baseline_pct99_us
            out["vs_baseline"] = round(ratio, 4)
            out["burn"] = ("improving" if ratio < 1.0 - self.drift_tol
                           else "degrading" if ratio > 1.0 + self.drift_tol
                           else "flat")
        return out


def baseline_pct99_from(path: str) -> Optional[float]:
    """The exact-tier pct99 of a committed serve-replay baseline
    (``SERVE_BENCH_r*.json`` — serve/replay.py result document); None
    when the file is unreadable or not of that family."""
    try:
        with open(path) as f:
            doc = json.load(f)
        exact = (doc.get("segmented") or {}).get("resolve_us", {}).get(
            "exact") or {}
        v = exact.get("pct99_us")
        return float(v) if v is not None else None
    except (OSError, ValueError, TypeError, AttributeError):
        return None


class MetricsSnapshotWriter:
    """Periodic atomic metric-snapshot documents, bounded ring per owner.

    Files are ``metrics-<owner>-<k>.json`` with ``k = seq % ring`` —
    the on-disk footprint of a process that snapshots every heartbeat
    for a month is ``ring`` files, not a month of files; each document
    carries its monotonic ``seq`` so readers (:func:`latest_snapshots`,
    the report CLI's ``--follow``) order them without trusting mtimes.
    Writes go through utils/atomic.py (fsync + rename) and every read
    in the document is non-blocking — the writer is safe to call from a
    heartbeat thread and from signal-trap paths alike."""

    def __init__(self, directory: str, owner: str, ring: int = 8,
                 slo: Optional[SloConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None):
        self.dir = directory
        self.owner = owner
        self.ring = max(1, int(ring))
        self.slo = slo
        self._registry = registry
        self._tracer = tracer
        self.seq = 0

    def path_for(self, seq: int) -> str:
        return os.path.join(
            self.dir, f"metrics-{self.owner}-{seq % self.ring}.json")

    def build(self, state: str = "serving",
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """The snapshot document (without writing it) — also the
        ``metrics`` verb's response body on the listen protocol."""
        from tenzing_tpu.obs.tracer import get_tracer

        registry = self._registry if self._registry is not None \
            else get_metrics()
        tracer = self._tracer if self._tracer is not None else get_tracer()
        doc: Dict[str, Any] = {
            "version": SNAPSHOT_VERSION,
            "kind": "metrics_snapshot",
            "owner": self.owner,
            "seq": self.seq,
            "written_at": time.time(),
            "state": state,
            "metrics": registry.to_json(block=False),
            "tracer": tracer.retention(),
        }
        if self.slo is not None:
            doc["slo"] = self.slo.block(registry)
        if extra:
            doc.update(extra)
        return doc

    def write(self, state: str = "serving",
              extra: Optional[Dict[str, Any]] = None) -> str:
        from tenzing_tpu.utils.atomic import atomic_dump_json

        doc = self.build(state=state, extra=extra)
        path = self.path_for(self.seq)
        os.makedirs(self.dir, exist_ok=True)
        atomic_dump_json(path, doc, prefix=".metrics.")
        self.seq += 1
        return path


def _snapshot_key(doc) -> tuple:
    try:
        at = float(doc.get("written_at", 0))
    except (TypeError, ValueError):
        at = 0.0
    return (at, doc.get("seq", -1))


def snapshot_history(directory: str) -> Dict[str, List[Dict[str, Any]]]:
    """Every readable snapshot document in ``directory``, grouped by
    owner and ordered oldest-first by ``(written_at, seq)`` — the ring
    as a short time series.  This is what multi-window evaluation
    (obs/alerts.py burn rates) reads: the newest document is one
    window, the whole ring is the other.  Wall-clock first, seq as the
    tiebreak: a restarted process starts over at seq 0 while the dead
    incarnation's high-seq documents still occupy the other ring slots
    — ordering by seq alone would show the dead process's state for up
    to ring-1 heartbeats.  Unreadable/foreign files are skipped: the
    readers must render whatever half-written fleet state exists."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("metrics-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("kind") != "metrics_snapshot":
            continue
        out.setdefault(doc.get("owner", "?"), []).append(doc)
    for docs in out.values():
        docs.sort(key=_snapshot_key)
    return out


def latest_snapshots(directory: str) -> Dict[str, Dict[str, Any]]:
    """The newest snapshot document per owner found in ``directory``
    (max ``(written_at, seq)`` wins — :func:`snapshot_history` for the
    ordering rationale)."""
    return {owner: docs[-1]
            for owner, docs in snapshot_history(directory).items() if docs}
