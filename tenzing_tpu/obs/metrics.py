"""Metrics registry: counters, gauges, histograms with percentile summaries.

The quantitative half of the telemetry subsystem: where the tracer answers
"what happened when", the registry answers "how much / how often / how
slow".  One process-global registry (:func:`get_metrics`) aggregates across
the whole decision loop — solver phase timings (via the ``utils/counters.py``
shim), benchmark cache hit rates, measurement counts — and serializes to one
JSON document (``bench.py --metrics-json``).

Histogram summaries use the same nearest-rank percentile convention as
``BenchResult`` (utils/numeric.py::percentile — a stdlib-only module, so the
import stays cycle-free) and retain raw observations up to a cap so archived
metrics can be re-derived offline without hot loops growing memory unbounded.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List

from tenzing_tpu.utils.numeric import percentile


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Distribution of observations with a percentile summary.

    Aggregates (count/sum/min/max) are exact and O(1) per observation; raw
    values are retained only up to ``max_raw`` for the percentile summary —
    a hot loop observing per-node timings (DFS enumeration) cannot grow
    memory without bound.  A truncated summary carries ``raw_retained`` and
    ``truncated: true`` so downstream tooling (e.g. the report CLI,
    obs/report.py) labels the percentiles prefix-only instead of silently
    treating them as full-series statistics."""

    __slots__ = ("name", "_lock", "_values", "_count", "_sum", "_min",
                 "_max", "_max_raw")

    def __init__(self, name: str, max_raw: int = 65536):
        self.name = name
        self._lock = threading.Lock()
        self._values: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._max_raw = max(1, max_raw)

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value
            if len(self._values) < self._max_raw:
                self._values.append(value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return self._sum

    def values(self) -> List[float]:
        """The retained raw observations (all of them below ``max_raw``)."""
        with self._lock:
            return list(self._values)

    def summary(self, block: bool = True) -> Dict[str, float]:
        """count/sum/min/max/mean + nearest-rank p50/p90/p99.

        ``block=False`` is the async-signal-safe read (bench.py's trap-path
        ``write_telemetry``): if the instrument lock cannot be acquired —
        the interrupted thread may hold it mid-``observe`` — the summary is
        computed from a GIL-atomic copy of the fields instead of blocking
        on a lock that will never be released."""
        acquired = self._lock.acquire(blocking=block)
        try:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
            xs = sorted(self._values)
        finally:
            if acquired:
                self._lock.release()
        if count == 0 or not xs:
            # xs can be empty at count > 0 only on a torn non-blocking read
            # (observe() bumps count before appending); report the exact
            # aggregates without percentiles rather than crash in the trap
            return {"count": count, "sum": total}
        out = {
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "mean": total / count,
            "p50": percentile(xs, 50),
            "p90": percentile(xs, 90),
            "p99": percentile(xs, 99),
        }
        if len(xs) < count:
            # the retained-raw cap truncated the series: the percentiles
            # cover only the first ``raw_retained`` of ``count``
            # observations.  ``truncated`` is the explicit marker readers
            # (the report CLI labels such percentiles "prefix-only") can
            # key on without comparing count vs raw_retained themselves.
            out["raw_retained"] = len(xs)
            out["truncated"] = True
        return out


class MetricsRegistry:
    """Get-or-create namespace of instruments; serializes to one JSON doc."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            inst = self._counters.get(name)
            if inst is None:
                inst = self._counters[name] = Counter(name)
            return inst

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            inst = self._gauges.get(name)
            if inst is None:
                inst = self._gauges[name] = Gauge(name)
            return inst

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            inst = self._histograms.get(name)
            if inst is None:
                inst = self._histograms[name] = Histogram(name)
            return inst

    def histograms(self) -> Dict[str, Histogram]:
        """Snapshot of the registered histograms (name -> instrument)."""
        with self._lock:
            return dict(self._histograms)

    @contextmanager
    def timer(self, name: str):
        """Time a block into histogram ``name`` (seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).observe(time.perf_counter() - t0)

    def to_json(self, block: bool = True) -> Dict[str, Any]:
        """Serialize every instrument.  ``block=False`` is the
        async-signal-safe variant (the trap path, bench.py
        ``write_telemetry``): registry and per-histogram locks are taken
        non-blocking with GIL-atomic dict/list copies as the fallback, so a
        signal handler can archive metrics even while the interrupted
        thread holds an instrument lock."""
        acquired = self._lock.acquire(blocking=block)
        try:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        finally:
            if acquired:
                self._lock.release()
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.summary(block=block)
                           for n, h in sorted(histograms.items())},
        }

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_GLOBAL = MetricsRegistry()


def get_metrics() -> MetricsRegistry:
    """The process-global registry (always live — recording into it is cheap
    and reading it is opt-in, so there is no enabled flag to thread around)."""
    return _GLOBAL


def set_metrics(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous one."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, registry
    return prev
