"""Cross-process trace context: one ``trace_id`` follows a request
through the whole serving fleet.

The PR-1 tracer is strictly per-process: a ``serve listen`` query, the
cold work item it enqueues, the drain daemon that claims it, the
daemon's subprocess drain, and the store merge that finally answers the
re-query each record spans into their own bundle, unlinkable from each
other.  This module is the link (docs/observability.md "Fleet telemetry
plane"): a tiny immutable :class:`TraceContext` — ``trace_id`` plus the
minting side's ``span_id`` — is

* **minted at ingress** (``serve listen`` per request; the resolver
  mints one itself when a caller arrives without one, so the one-shot
  ``serve query`` CLI participates identically);
* **made ambient** with :func:`use` (a thread-local stack with a
  process-global fallback, :func:`set_process_default`, for processes
  whose whole lifetime serves one request — a daemon's drain child);
* **stamped automatically** onto every span and event the tracer
  records while a context is ambient (``trace_id`` / ``parent_span``
  attrs — obs/tracer.py consults :func:`current_trace_attrs`);
* **carried across process boundaries** two ways, deliberately
  redundant: the :data:`TRACE_ENV` environment variable (cheap, works
  for any child) and the work item's checkpoint envelope (the
  ``trace`` key serve/store.py ``WorkQueue`` stamps) — the envelope is
  the SIGKILL-survivable copy: a successor daemon reclaiming a dead
  worker's lease re-reads the item from disk and resumes the drain
  under the *same* trace_id, no live parent required.

Everything here is stdlib-only and imports nothing from the rest of
``obs`` (the tracer imports *us*, not vice versa — no cycle).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional

# the environment variable a parent sets for its children ("trace_id:span_id")
TRACE_ENV = "TENZING_TRACE_CONTEXT"


# minting entropy is buffered: context ids come from ``os.urandom`` —
# never ``random``, so the solvers' seeded RNG streams stay untouched —
# but one urandom *syscall* per id is real microseconds on the serving
# ingress (each request mints two).  One 4 KiB read amortizes the
# syscall over 256 mints; the buffer is reset in a forked child so two
# processes can never replay the same entropy window.
_MINT_REFILL = 4096
_mint_lock = threading.Lock()
_mint_buf = b""
_mint_pos = 0


def _mint_reset() -> None:
    global _mint_lock, _mint_buf, _mint_pos
    # rebind the lock too: a child forked while another thread held it
    # would otherwise deadlock on its first mint
    _mint_lock = threading.Lock()
    _mint_buf = b""
    _mint_pos = 0


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_mint_reset)


def _mint_id(nbytes: int = 8) -> str:
    """A random hex id (default 16 hex chars) from the buffered urandom
    pool (module comment above)."""
    global _mint_buf, _mint_pos
    with _mint_lock:
        if _mint_pos + nbytes > len(_mint_buf):
            _mint_buf = os.urandom(max(_MINT_REFILL, nbytes))
            _mint_pos = 0
        out = _mint_buf[_mint_pos:_mint_pos + nbytes]
        _mint_pos += nbytes
    return out.hex()


@dataclass(frozen=True)
class TraceContext:
    """One request's identity: ``trace_id`` names the whole journey,
    ``span_id`` the hop that handed it to us (the remote parent)."""

    trace_id: str
    span_id: str

    def child(self) -> "TraceContext":
        """The context to hand DOWNSTREAM: same trace, fresh hop id."""
        return TraceContext(self.trace_id, _mint_id())

    def to_json(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_env_value(self) -> str:
        return f"{self.trace_id}:{self.span_id}"


def new_trace() -> TraceContext:
    """Mint a fresh context — THE ingress call (one per request)."""
    return TraceContext(_mint_id(), _mint_id())


def from_json(doc: Any) -> Optional[TraceContext]:
    """A context from its envelope form; None for anything malformed —
    a torn ``trace`` key must never fail the drain it rides with."""
    if not isinstance(doc, dict):
        return None
    tid, sid = doc.get("trace_id"), doc.get("span_id")
    if not (isinstance(tid, str) and tid):
        return None
    return TraceContext(tid, sid if isinstance(sid, str) and sid else "0")


def from_env(environ: Optional[Dict[str, str]] = None) -> Optional[TraceContext]:
    """The context a parent process exported via :data:`TRACE_ENV`."""
    raw = (environ if environ is not None else os.environ).get(TRACE_ENV)
    if not raw:
        return None
    tid, _, sid = raw.partition(":")
    if not tid:
        return None
    return TraceContext(tid, sid or "0")


def to_env(environ: Dict[str, str], ctx: Optional[TraceContext]) -> Dict[str, str]:
    """Stamp ``ctx`` into an environment mapping (for a child process);
    a None context leaves the mapping untouched."""
    if ctx is not None:
        environ[TRACE_ENV] = ctx.to_env_value()
    return environ


# -- ambient context --------------------------------------------------------

_local = threading.local()
_process_default: Optional[TraceContext] = None
_default_lock = threading.Lock()


def current() -> Optional[TraceContext]:
    """The ambient context: this thread's innermost :func:`use`, else
    the process default (set by a drain child adopting its parent's
    envelope — worker threads inherit it without any threading of
    arguments)."""
    stack = getattr(_local, "stack", None)
    if stack:
        return stack[-1]
    return _process_default


class _Use:
    """Re-entrant-friendly context manager pushing one context onto the
    thread-local stack; ``use(None)`` is a no-op (callers never need a
    conditional ``with``)."""

    __slots__ = ("ctx", "_pushed")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx
        self._pushed = False

    def __enter__(self) -> Optional[TraceContext]:
        if self.ctx is not None:
            stack = getattr(_local, "stack", None)
            if stack is None:
                stack = _local.stack = []
            stack.append(self.ctx)
            self._pushed = True
        return self.ctx

    def __exit__(self, *exc) -> None:
        if self._pushed:
            _local.stack.pop()


def use(ctx: Optional[TraceContext]) -> _Use:
    """``with use(ctx): ...`` — make ``ctx`` ambient on this thread."""
    return _Use(ctx)


def set_process_default(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Set (or clear, with None) the process-wide fallback context;
    returns the previous default so a scoped caller can restore it."""
    global _process_default
    with _default_lock:
        prev, _process_default = _process_default, ctx
    return prev


def current_trace_attrs() -> Optional[Dict[str, str]]:
    """What the tracer stamps onto a record while a context is ambient
    (obs/tracer.py) — None (the common case) costs one thread-local
    probe and one global read."""
    ctx = current()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_span": ctx.span_id}
