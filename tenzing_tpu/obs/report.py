"""Corpus analytics + regression gate: ``python -m tenzing_tpu.obs.report``.

Five rounds of searching left a measurement corpus on disk — recorded
search databases (``experiments/*_search_tpu*.csv``), driver JSON verdicts
(``BENCH_*.json``), checkpoint journals, quarantines, telemetry bundles.
This CLI mines them into one markdown report and implements the
**noise-aware regression check** the CI and future PRs gate on
(docs/observability.md, "Attribution").

Sections (each optional, driven by which inputs are given):

* ``--csv GLOB``   — recorded-database trajectory per workload: rows,
  naive anchor, best in-file paired ratio (the same regime-honest ranking
  bench/recorded.py warm-starts from — numeric parse only, no graph);
* ``--bench GLOB`` — driver-JSON trajectory: value / vs_baseline /
  naive regime, plus the fault (quarantine, degradation, verification),
  perf (compile + prefetch economics) and attrib (overlap efficiency,
  dispatch overhead) meta blocks;
* ``--journal DIR``— checkpoint mining: journaled measurements by
  provenance and fidelity, batch replays, quarantine contents;
* ``--trace GLOB`` — telemetry-bundle mining: where the wall went (top
  spans by total duration), event counts;
* ``--metrics GLOB`` — metrics-JSON histograms; summaries whose raw
  series was truncated (``truncated: true`` — obs/metrics.py) are labeled
  **prefix-only** rather than passed off as full-series percentiles;
* ``--store PATH [--queue-dir DIR]`` — schedule-serving store mining
  (docs/serving.md): records per workload/fingerprint, best stored
  ``vs_naive``, refinement/unsound flags, tenants, and the cold-request
  work-queue depth by reason.

Regression check (``--check FRESH --baseline BASELINE [--tol T]``):
compares two driver JSONs (raw driver lines or the ``{"parsed": ...}``
BENCH wrapper).  The primary series is ``vs_baseline`` (the paired
speedup — regime-immune by construction); the secondary is the
naive-relative value (``value / naive_us``).  Noise-awareness reuses
bench/randomness.py's runs test: when the fresh JSON's attrib block
carries the winner's raw measurement series and that series fails the
i.i.d. test, a would-be regression is reported ``inconclusive`` (drift or
interference — re-measure) instead of flagged.  Exit status: 0 ok /
inconclusive, 1 regression, 2 usage error.

The same flags accept the **SERVE_BENCH family** (serve/replay.py
trace-replay documents, ``kind: "serve_trace_replay"``): the primary
series becomes the segmented exact-tier ``pct99_us`` (a ceiling —
higher is a regression), the secondaries are per-query verifier calls
and shed count reappearing, and the noise rule runs the same runs test
over the document's raw ``exact_samples_us`` series — a serve-replay
pct99 regression fails the build exactly like a bench one.

``--follow`` is the live fleet view (docs/observability.md "Fleet
telemetry plane"): tail the ``status-*.json`` and ``metrics-*.json``
documents of every serve loop and drain daemon under ``--store`` /
``--queue-dir``, rendering liveness, queue depth/age, tier hit mix,
and SLO state every ``--interval`` seconds.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple


# -- driver-JSON loading ----------------------------------------------------

def load_driver_json(path: str) -> Dict[str, Any]:
    """A driver verdict dict from ``path``: accepts a raw driver JSON
    object/line, a file whose LAST line is the driver JSON (bench.py
    stdout capture), or the repo's ``BENCH_*.json`` wrapper (uses its
    ``parsed`` field)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            if "parsed" in doc and isinstance(doc["parsed"], dict):
                return doc["parsed"]
            if "metric" in doc:
                return doc
    except ValueError:
        pass
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            return doc
    raise ValueError(f"{path}: no driver JSON found")


def _load_check_doc(path: str) -> Dict[str, Any]:
    """A document for the regression check: a SERVE_BENCH trace-replay
    result (``kind: "serve_trace_replay"``) is returned whole; anything
    else goes through the driver-JSON loader."""
    try:
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and \
                doc.get("kind") == "serve_trace_replay":
            return doc
    except ValueError:
        pass
    return load_driver_json(path)


# -- regression check -------------------------------------------------------

def check_regression(fresh: Dict[str, Any], baseline: Dict[str, Any],
                     tol: float = 0.05) -> Dict[str, Any]:
    """Noise-aware comparison of a fresh driver verdict against a
    committed baseline (see module docstring).  Returns ``{"verdict":
    "ok"|"regression"|"inconclusive", "reasons": [...], ...}``."""
    reasons: List[str] = []
    checks: Dict[str, Any] = {}

    f_vs, b_vs = fresh.get("vs_baseline"), baseline.get("vs_baseline")
    if f_vs is not None and b_vs is not None and b_vs > 0:
        floor = b_vs * (1.0 - tol)
        checks["vs_baseline"] = {"fresh": f_vs, "baseline": b_vs,
                                 "floor": round(floor, 4)}
        if f_vs < floor:
            reasons.append(
                f"vs_baseline {f_vs:.4f} < {floor:.4f} "
                f"(baseline {b_vs:.4f} - {tol:.0%})")

    # naive-relative value: value/naive_us is regime-honest where raw value
    # is not (chip regimes swing >1.3x run to run — bench/recorded.py)
    def rel(d):
        v, n = d.get("value"), d.get("naive_us")
        return (v / n) if v and n else None

    f_rel, b_rel = rel(fresh), rel(baseline)
    if f_rel is not None and b_rel is not None and b_rel > 0:
        ceil = b_rel * (1.0 + tol)
        checks["relative_value"] = {"fresh": round(f_rel, 4),
                                    "baseline": round(b_rel, 4),
                                    "ceiling": round(ceil, 4)}
        if f_rel > ceil:
            reasons.append(
                f"value/naive {f_rel:.4f} > {ceil:.4f} "
                f"(baseline {b_rel:.4f} + {tol:.0%})")

    verdict = "regression" if reasons else "ok"
    times = (fresh.get("attrib") or {}).get("measured_times")
    verdict, checks2 = _noise_downgrade(verdict, reasons, times)
    checks.update(checks2)
    return {"verdict": verdict, "tol": tol, "reasons": reasons,
            "checks": checks}


def _noise_downgrade(verdict: str, reasons: List[str],
                     times) -> Tuple[str, Dict[str, Any]]:
    """THE shared noise rule: a would-be regression whose fresh raw
    series fails bench/randomness.py's i.i.d. runs test downgrades to
    ``inconclusive`` — the measurement, not the code, is suspect.  Used
    by both the driver-verdict and the serve-replay check."""
    checks: Dict[str, Any] = {}
    if verdict == "regression" and times and len(times) >= 8:
        from tenzing_tpu.bench.randomness import runs_test_z

        z_crit = 1.96  # is_random's 95%-confidence default
        z = runs_test_z(list(times))
        checks["runs_test_z"] = round(z, 3)
        if abs(z) > z_crit:
            verdict = "inconclusive"
            reasons.append(
                f"fresh measurement series fails the runs test "
                f"(|Z|={abs(z):.2f} > {z_crit}) — re-measure before "
                "trusting the regression")
    return verdict, checks


def check_serve_regression(fresh: Dict[str, Any], baseline: Dict[str, Any],
                           tol: float = 0.25) -> Dict[str, Any]:
    """The SERVE_BENCH-family twin of :func:`check_regression`
    (module docstring): segmented exact-tier pct99 as a ceiling,
    verifier-call and shed reappearance as secondaries, the same
    noise-aware downgrade over the fresh ``exact_samples_us`` series.
    The default tolerance is wider than the bench gate's — wall-clock
    microsecond latencies swing more host-to-host than paired ratios."""
    reasons: List[str] = []
    checks: Dict[str, Any] = {}

    def exact(doc):
        return ((doc.get("segmented") or {}).get("resolve_us") or {}).get(
            "exact") or {}

    f_p99, b_p99 = exact(fresh).get("pct99_us"), \
        exact(baseline).get("pct99_us")
    if f_p99 is not None and b_p99:
        ceil = b_p99 * (1.0 + tol)
        checks["exact_pct99_us"] = {"fresh": f_p99, "baseline": b_p99,
                                    "ceiling": round(ceil, 1)}
        if f_p99 > ceil:
            reasons.append(
                f"segmented exact pct99 {f_p99:.1f}us > {ceil:.1f}us "
                f"(baseline {b_p99:.1f}us + {tol:.0%})")
    f_ver = (fresh.get("segmented") or {}).get("verifier_calls")
    b_ver = (baseline.get("segmented") or {}).get("verifier_calls")
    if f_ver is not None and b_ver is not None:
        checks["verifier_calls"] = {"fresh": f_ver, "baseline": b_ver}
        if f_ver > b_ver:
            # zero per-query verifier invocations is an admission-time
            # design guarantee, not a tolerance band (docs/serving.md)
            reasons.append(
                f"per-query verifier calls reappeared "
                f"({b_ver} -> {f_ver})")
    f_shed = (fresh.get("segmented") or {}).get("shed")
    b_shed = (baseline.get("segmented") or {}).get("shed")
    if f_shed is not None and b_shed is not None:
        checks["shed"] = {"fresh": f_shed, "baseline": b_shed}
        if f_shed > b_shed:
            reasons.append(f"shed responses grew ({b_shed} -> {f_shed}) "
                           "at the same paced QPS")

    # differential localization (obs/causal.py): when the per-phase
    # samples are present in both documents, name the segment that moved
    # — "cache_probe regressed 3.1x" steers a fix; a bare pct99 doesn't
    from tenzing_tpu.obs.causal import localize_phases

    loc = localize_phases(fresh, baseline, tol=tol)
    if loc["compared"]:
        checks["segments"] = loc
        for m in loc["moved"]:
            reasons.append(
                f"phase '{m['segment']}' pct99 regressed "
                f"{m['ratio']:.1f}x ({m['baseline_pct99_us']:.1f}us -> "
                f"{m['fresh_pct99_us']:.1f}us)")

    verdict = "regression" if reasons else "ok"
    samples = (fresh.get("segmented") or {}).get("exact_samples_us")
    verdict, checks2 = _noise_downgrade(verdict, reasons, samples)
    checks.update(checks2)

    # measured host-noise floors (obs/noise.py): a fresh document from a
    # materially noisier/quieter host is not comparable — downgrade any
    # would-be regression rather than blame the code for the scheduler
    from tenzing_tpu.obs.noise import floor_vs_tail, floors_differ

    f_noise, b_noise = fresh.get("host_noise"), baseline.get("host_noise")
    fvt = floor_vs_tail(f_noise, f_p99)
    if fvt is not None:
        checks["host_noise"] = fvt
    diff = floors_differ(f_noise, b_noise)
    if diff is not None:
        checks["host_floors"] = diff
        if verdict == "regression":
            verdict = "inconclusive"
            reasons.append(
                f"hosts are not comparable: {diff} — re-measure both "
                "documents on one host before trusting the regression")
    return {"verdict": verdict, "tol": tol, "reasons": reasons,
            "checks": checks, "family": "serve_trace_replay"}


# -- recorded-database mining (numeric parse, no graph) ---------------------

def _csv_rows(path: str) -> List[Tuple[int, float, str]]:
    """(row idx, pct50, fidelity) per parseable row of a recorded DB."""
    from tenzing_tpu.bench.benchmarker import CSV_DELIM, split_fidelity

    out = []
    with open(path) as f:
        for line in f:
            cells = line.rstrip("\n").split(CSV_DELIM)
            try:
                idx = int(cells[0])
                pct50 = float(cells[3])
                fid, _ = split_fidelity(cells)
            except (ValueError, IndexError):
                continue
            out.append((idx, pct50, fid))
    return out


def _workload_of(path: str) -> str:
    base = os.path.basename(path)
    return base.split("_")[0] if "_" in base else base


def corpus_section(csv_paths: List[str]) -> List[str]:
    from tenzing_tpu.bench.recorded import naive_anchor_of

    lines = ["## Recorded search databases", "",
             "| file | workload | rows (full) | naive anchor (us) | "
             "best in-file ratio | best pct50 (us) |",
             "|---|---|---|---|---|---|"]
    best_by_wl: Dict[str, float] = {}
    for path in csv_paths:
        try:
            rows = _csv_rows(path)
            anchor = naive_anchor_of(path)
        except OSError as e:
            lines.append(f"| {os.path.basename(path)} | — | unreadable "
                         f"({e.__class__.__name__}) | | | |")
            continue
        full = [(i, p) for i, p, fid in rows
                if fid == "full" and i > 0 and p > 0]
        wl = _workload_of(path)
        if anchor and full:
            best_p = min(p for _, p in full)
            ratio = anchor / best_p
            best_by_wl[wl] = max(best_by_wl.get(wl, 0.0), ratio)
            lines.append(
                f"| {os.path.basename(path)} | {wl} | {len(rows)} "
                f"({len(full)}) | {anchor * 1e6:.1f} | {ratio:.3f} | "
                f"{best_p * 1e6:.1f} |")
        else:
            lines.append(
                f"| {os.path.basename(path)} | {wl} | {len(rows)} "
                f"({len(full)}) | {'—' if not anchor else f'{anchor*1e6:.1f}'}"
                " | — | — |")
    if best_by_wl:
        lines += ["", "Best recorded in-file paired ratio per workload: " +
                  ", ".join(f"**{wl}** {r:.3f}x"
                            for wl, r in sorted(best_by_wl.items()))]
    lines.append("")
    return lines


# -- driver-JSON mining -----------------------------------------------------

def bench_section(paths: List[str]) -> List[str]:
    lines = ["## Driver verdicts", "",
             "| file | metric | value (us) | vs_baseline | naive (us) | "
             "compile (s) | prefetch hit/issued | quarantined | verified | "
             "overlap eff | dispatch ovh (us) |",
             "|---|---|---|---|---|---|---|---|---|---|---|"]
    fused_lines: List[str] = []
    chunk_lines: List[str] = []
    synth_lines: List[str] = []
    for path in paths:
        try:
            d = load_driver_json(path)
        except (OSError, ValueError) as e:
            lines.append(f"| {os.path.basename(path)} | unreadable "
                         f"({e.__class__.__name__}) | | | | | | | | | |")
            continue
        perf = d.get("perf") or {}
        pf = perf.get("prefetch") or {}
        fault = d.get("fault") or {}
        at = d.get("attrib") or {}
        ver = fault.get("verified")
        lines.append(
            "| {f} | {m} | {v} | {vs} | {n} | {c} | {ph}/{pi} | {q} | {ok} "
            "| {oe} | {do} |".format(
                f=os.path.basename(path), m=d.get("metric", "—"),
                v=d.get("value", "—"), vs=d.get("vs_baseline", "—"),
                n=d.get("naive_us", "—"),
                c=perf.get("compile_secs", "—"),
                ph=pf.get("hits", "—"), pi=pf.get("issued", "—"),
                q=fault.get("quarantined", 0),
                ok=("—" if ver is None else ver),
                oe=at.get("overlap_efficiency", "—"),
                do=at.get("dispatch_overhead_us", "—")))
        ch = perf.get("chunked")
        if ch:
            # op-chunking economics (docs/performance.md, "Chunked
            # overlap"): what the roofline let onto the menus, what the
            # search visited/chose, and the hidden comm the chunking
            # bought — estimated bound vs stepped-timeline measurement
            if "error" in ch and "menus" not in ch:
                chunk_lines.append(
                    f"- `{os.path.basename(path)}`: chunk provenance "
                    f"failed ({ch['error']})")
            else:
                menus = ch.get("menus") or {}
                n_gt1 = sum(1 for m in menus.values()
                            if [c for c in m.get("counts", []) if c > 1])
                chosen = ch.get("chosen") or {}
                hc = ch.get("hidden_comm_us") or {}
                msd = hc.get("measured")
                chunk_lines.append(
                    f"- `{os.path.basename(path)}`: {len(menus)} menu(s) "
                    f"({n_gt1} with counts>1), searched counts "
                    f"{ch.get('searched_counts', [])} over "
                    f"{ch.get('n_candidates_chunked', 0)} candidate(s), "
                    f"winner {'unchunked' if not chosen else chosen}, "
                    f"hidden comm est {hc.get('estimated', 0)}us / "
                    f"measured {'—' if msd is None else f'{msd}us'}"
                    + (f" — {ch['note']}" if ch.get("note") else ""))
        sy = perf.get("synth")
        if sy:
            # synthesized-collective economics (docs/performance.md,
            # "Synthesized collectives"): the sketch menus the pricing let
            # stand next to the fixed engine, what the search visited and
            # chose, and the est-vs-measured comm of the decomposition
            if "error" in sy and "menus" not in sy:
                synth_lines.append(
                    f"- `{os.path.basename(path)}`: synth provenance "
                    f"failed ({sy['error']})")
            else:
                smenus = sy.get("menus") or {}
                n_alt = sum(1 for m in smenus.values()
                            if len(m.get("menu", [])) > 1)
                schosen = sy.get("chosen") or {}
                msd = sy.get("measured_hidden_us")
                synth_lines.append(
                    f"- `{os.path.basename(path)}`: {len(smenus)} site(s) "
                    f"({n_alt} with sketch alternatives), searched "
                    f"{sy.get('searched_sketches', [])} over "
                    f"{sy.get('n_candidates_synth', 0)} candidate(s), "
                    f"winner {'fixed-engine' if not schosen else schosen}, "
                    f"est comm {sy.get('est_comm_us', 0)}us / hidden "
                    f"measured {'—' if msd is None else f'{msd}us'}, "
                    f"verified {sy.get('verified', False)}"
                    + (f" — {sy['note']}" if sy.get("note") else ""))
        fu = perf.get("fused")
        if fu:
            # megakernel-fusion economics (docs/performance.md): regions
            # lowered, tile chosen, and the dispatch overhead the fused
            # program removed vs its stepped twin
            if "error" in fu and "regions" not in fu:
                fused_lines.append(
                    f"- `{os.path.basename(path)}`: fusion failed "
                    f"({fu['error']})")
                continue
            do = fu.get("dispatch_overhead_us") or {}
            before, after = do.get("before"), do.get("after")
            removed = (f"{before - after:.1f}us removed "
                       f"({before} -> {after})"
                       if before is not None and after is not None else "—")
            fused_lines.append(
                f"- `{os.path.basename(path)}`: {fu.get('regions', 0)} "
                f"region(s) over {fu.get('fused_ops', 0)}/"
                f"{fu.get('n_ops_total', 0)} ops, tiles "
                f"{(fu.get('tiles') or {}).get('chosen', 1)}, dispatch "
                f"overhead {removed}, verified "
                f"{fu.get('verified', False)}")
    lines.append("")
    if fused_lines:
        lines += ["### Megakernel fusion", ""] + fused_lines + [""]
    if chunk_lines:
        lines += ["### Chunked overlap", ""] + chunk_lines + [""]
    if synth_lines:
        lines += ["### Synthesized collectives", ""] + synth_lines + [""]
    return lines


# -- checkpoint-journal mining ----------------------------------------------

def journal_section(dirs: List[str]) -> List[str]:
    from tenzing_tpu.utils.numeric import percentile

    lines = ["## Checkpoint journals", ""]
    for d in dirs:
        jpath = os.path.join(d, "measurements.jsonl")
        qpath = os.path.join(d, "quarantine.json")
        lines.append(f"### `{d}`")
        if not os.path.exists(jpath):
            lines += ["", "no measurement journal", ""]
        else:
            by_prov: Dict[str, int] = {}
            pct50s: List[float] = []
            batches = 0
            skipped = 0
            with open(jpath) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        j = json.loads(line)
                    except ValueError:
                        skipped += 1  # torn tail line
                        continue
                    if "batch" in j:
                        batches += 1
                        continue
                    prov = j.get("prov", "measured")
                    by_prov[prov] = by_prov.get(prov, 0) + 1
                    try:
                        pct50s.append(float(j["result"]["pct50"]))
                    except (KeyError, TypeError, ValueError):
                        pass
            lines.append("")
            lines.append(f"- measurements: {sum(by_prov.values())} (" +
                         ", ".join(f"{k}={v}"
                                   for k, v in sorted(by_prov.items())) +
                         f"), paired batches: {batches}" +
                         (f", torn/skipped lines: {skipped}" if skipped
                          else ""))
            if pct50s:
                xs = sorted(pct50s)
                lines.append(
                    f"- journaled pct50 (us): min {xs[0]*1e6:.1f} / p50 "
                    f"{percentile(xs, 50)*1e6:.1f} / max {xs[-1]*1e6:.1f}")
        if os.path.exists(qpath):
            try:
                with open(qpath) as f:
                    q = json.load(f)
                entries = q.get("entries", {})
                by_cls: Dict[str, int] = {}
                for e in entries.values():
                    c = e.get("error_class", "?")
                    by_cls[c] = by_cls.get(c, 0) + 1
                lines.append(
                    f"- quarantine: {len(entries)} schedule(s)" +
                    (" (" + ", ".join(f"{k}={v}"
                                      for k, v in sorted(by_cls.items())) +
                     ")" if by_cls else ""))
            except (OSError, ValueError):
                lines.append("- quarantine: unreadable")
        lines.append("")
    return lines


# -- telemetry-bundle mining ------------------------------------------------

def trace_section(paths: List[str], top: int = 12) -> List[str]:
    from tenzing_tpu.obs.export import read_jsonl

    lines = ["## Telemetry bundles", ""]
    for path in paths:
        try:
            recs = read_jsonl(path)
        except (OSError, ValueError) as e:
            lines += [f"### `{path}`", "", f"unreadable ({e})", ""]
            continue
        span_tot: Dict[str, float] = {}
        span_n: Dict[str, int] = {}
        ev_n: Dict[str, int] = {}
        for r in recs:
            if r.get("kind") == "span":
                nm = r.get("name", "?")
                span_tot[nm] = span_tot.get(nm, 0.0) + float(
                    r.get("dur_us", 0.0))
                span_n[nm] = span_n.get(nm, 0) + 1
            elif r.get("kind") == "event":
                nm = r.get("name", "?")
                ev_n[nm] = ev_n.get(nm, 0) + 1
        lines += [f"### `{path}`", "",
                  f"- records: {len(recs)} ({sum(span_n.values())} spans, "
                  f"{sum(ev_n.values())} events)",
                  "", "| span | count | total (s) |", "|---|---|---|"]
        for nm in sorted(span_tot, key=lambda n: -span_tot[n])[:top]:
            lines.append(f"| {nm} | {span_n[nm]} | "
                         f"{span_tot[nm] / 1e6:.3f} |")
        if ev_n:
            lines += ["", "events: " + ", ".join(
                f"{nm}={ev_n[nm]}"
                for nm in sorted(ev_n, key=lambda n: -ev_n[n])[:top])]
        lines.append("")
    return lines


# -- metrics-JSON mining ----------------------------------------------------

def metrics_section(paths: List[str], top: int = 12) -> List[str]:
    lines = ["## Metrics", ""]
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            lines += [f"### `{path}`", "", f"unreadable ({e})", ""]
            continue
        hists = doc.get("histograms", {})
        lines += [f"### `{path}`", "",
                  "| histogram | count | sum | p50 | p99 | coverage |",
                  "|---|---|---|---|---|---|"]
        for nm in sorted(hists,
                         key=lambda n: -hists[n].get("sum", 0.0))[:top]:
            h = hists[nm]
            if h.get("window"):
                # windowed retention (obs/metrics.py): percentiles cover
                # the most recent raw_retained observations
                cov = (f"recent-window ({h.get('raw_retained', '?')}/"
                       f"{h.get('count', '?')})")
            elif h.get("truncated") or "raw_retained" in h:
                # obs/metrics.py Histogram.summary: the raw series was
                # capped; percentiles cover only the first raw_retained.
                # Legacy summaries (pre-``truncated`` flag) carried only
                # raw_retained — label those prefix-only too.
                cov = (f"prefix-only ({h.get('raw_retained', '?')}/"
                       f"{h.get('count', '?')})")
            else:
                cov = "full"
            lines.append(
                f"| {nm} | {h.get('count', 0)} | "
                f"{h.get('sum', 0.0):.4g} | {h.get('p50', '—')} | "
                f"{h.get('p99', '—')} | {cov} |")
        lines.append("")
    return lines


# -- serving-store mining ---------------------------------------------------

def store_section(store_paths: List[str],
                  queue_dir: Optional[str] = None) -> List[str]:
    """The schedule-serving store as a report section (docs/serving.md):
    what the fleet can answer without a search, and what is queued.
    Handles both backends via ``open_store`` — segmented directories gain
    a per-bucket segment table, the compaction ledger, the admission
    tally, and any serve-loop status documents found in the store."""
    from tenzing_tpu.serve.store import open_store

    lines = ["## Schedule-serving stores", ""]
    for path in store_paths:
        store = None
        if os.path.exists(path):
            # read-only: quarantine_corrupt=False means an unreadable or
            # version-mismatched file is reported but LEFT IN PLACE for
            # the serving process to quarantine — a diagnostics command
            # must never rename the store it was asked to describe
            notes: List[str] = []
            store = open_store(path, log=notes.append,
                               quarantine_corrupt=False)
            if notes and len(store) == 0:
                lines += [f"### `{path}`", "", notes[0], ""]
                continue
        if store is None or len(store) == 0:
            lines += [f"### `{path}`", "", "empty or missing store", ""]
            continue
        lines += [f"### `{path}`", "",
                  "| workload | fingerprint | schedules | best vs_naive | "
                  "flagged | tenants |",
                  "|---|---|---|---|---|---|"]
        for exact in sorted(store.entries):
            recs = list(store.entries[exact].values())
            best = store.best(exact)
            flagged = sum(1 for r in recs if any(r.get("flags", {}).values()))
            tenants = sorted({r.get("provenance", {}).get("tenant", "?")
                              for r in recs})
            lines.append(
                f"| {best.get('workload', '?')} | `{exact[:12]}` | "
                f"{len(recs)} | {best.get('vs_naive', 0):.3f} | {flagged} | "
                f"{', '.join(tenants)} |")
        st = store.stats()
        lines += ["",
                  f"- records: {st['records']} across "
                  f"{st['fingerprints']} fingerprint(s); "
                  f"{st['flagged']} flagged; "
                  f"{st['skipped_on_load']} skipped on load", ""]
        if st.get("backend") == "segmented":
            lines += segment_lines(st)
        if os.path.isdir(path):
            lines += serve_status_lines(path)
            lines += reqlog_lines(path)
    if queue_dir is not None:
        if not os.path.isdir(queue_dir):
            # surface the operator error (a typo'd path) instead of
            # silently creating it and reporting an empty queue
            lines += [f"### work queue `{queue_dir}`", "",
                      "missing directory", ""]
            return lines
        lines += queue_section(queue_dir)
    return lines


def segment_lines(st: Dict[str, Any]) -> List[str]:
    """The segmented-store internals (serve/segments.py stats): what the
    compactor sees — per-bucket segment counts, live/orphan/damage
    tallies, the admission verdicts, and the compaction ledger tail."""
    seg = st.get("segments", {})
    lines = ["#### segments", "",
             "| bucket | segments | live | records | bytes |",
             "|---|---|---|---|---|"]
    for bucket, b in sorted(st.get("by_bucket", {}).items()):
        lines.append(f"| `{bucket[:12]}` | {b.get('segments', 0)} | "
                     f"{b.get('live', 0)} | {b.get('records', 0)} | "
                     f"{b.get('bytes', 0)} |")
    lines += ["",
              f"- segments: {seg.get('count', 0)} "
              f"({seg.get('bytes', 0)} bytes); "
              f"orphans {seg.get('orphans', 0)}, "
              f"missing {seg.get('missing', 0)}, "
              f"quarantined {seg.get('quarantined', 0)}, "
              f"newer-skipped {seg.get('newer_skipped', 0)}; "
              f"checksum-failed records {st.get('checksum_failed', 0)}, "
              f"salvaged {st.get('salvaged', 0)}"]
    adm = st.get("admission", {})
    lines.append(
        f"- admission: {adm.get('verified', 0)} verified / "
        f"{adm.get('unsound', 0)} unsound (never served) / "
        f"{adm.get('unstamped', 0)} unstamped (lazy-verified)")
    last = st.get("last_compaction")
    if last:
        lines.append(
            f"- compactions: {st.get('compactions', 0)} ledgered; last: "
            f"bucket `{str(last.get('bucket', '?'))[:12]}` "
            f"{len(last.get('inputs', []))} -> 1 "
            f"({last.get('records', 0)} record(s)) by "
            f"{last.get('owner', '?')}")
    else:
        lines.append("- compactions: none ledgered")
    lines.append("")
    return lines


def _fastpath_rates(counters: Dict[str, Any]) -> Optional[str]:
    """The fast-path economics line (docs/serving.md "Fast path") from
    a metric snapshot's counter block: memo and fingerprint-cache hit
    rates + memo invalidations.  None when the process never served
    through either cache (nothing to rate)."""
    def rate(hits_key, misses_key):
        h = counters.get(hits_key, 0)
        m = counters.get(misses_key, 0)
        return (h, m, h / (h + m)) if (h + m) else None

    memo = rate("serve.memo.hits", "serve.memo.misses")
    fpc = rate("serve.fp_cache.hits", "serve.fp_cache.misses")
    if memo is None and fpc is None:
        return None
    parts = []
    if memo is not None:
        parts.append(f"memo hit rate {memo[2]:.1%} "
                     f"({memo[0]}/{memo[0] + memo[1]}, "
                     f"{counters.get('serve.memo.invalidations', 0)} "
                     "invalidated)")
    if fpc is not None:
        parts.append(f"fp-cache hit rate {fpc[2]:.1%} "
                     f"({fpc[0]}/{fpc[0] + fpc[1]})")
    return "fast path: " + ", ".join(parts)


def serve_status_lines(store_dir: str) -> List[str]:
    """Serve-loop status documents (serve/listen.py ``status-*.json``)
    found in a segmented store directory: liveness staleness + the
    served/shed/timeout economics — the same probe-target treatment the
    queue section gives daemon status docs.  Each loop's fast-path
    cache economics (memo + fingerprint-cache hit rates) render from
    its newest metric snapshot."""
    import time as _time

    from tenzing_tpu.obs.metrics import latest_snapshots

    snapshots = latest_snapshots(store_dir)
    lines: List[str] = []
    now = _time.time()
    for name in sorted(os.listdir(store_dir)):
        if not (name.startswith("status-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(store_dir, name)) as f:
                st = json.load(f)
        except (OSError, ValueError):
            lines.append(f"- service `{name}`: unreadable")
            continue
        if st.get("kind") != "serve_loop":
            continue
        c = st.get("counters", {})
        stale = now - float(st.get("heartbeat_at", 0))
        lines.append(
            f"- service `{st.get('owner', name)}`: {st.get('state')}, "
            f"heartbeat {stale:.1f}s ago — requests "
            f"{c.get('requests', 0)} (exact {c.get('served_exact', 0)}, "
            f"near {c.get('served_near', 0)}, cold "
            f"{c.get('served_cold', 0)}), shed {c.get('shed', 0)}, "
            f"timeouts {c.get('timeouts', 0)}, queue depth "
            f"{st.get('queue_depth', 0)}")
        snap = snapshots.get(st.get("owner"))
        if snap:
            rates = _fastpath_rates(
                (snap.get("metrics") or {}).get("counters") or {})
            if rates:
                lines.append(f"  - {rates}")
    if lines:
        lines.append("")
    return lines


def reqlog_lines(store_dir: str) -> List[str]:
    """The watchtower's recording state under a store directory
    (serve/reqlog.py, conventionally ``<store>/reqlog``): recorded
    traffic coverage and the exemplar bundles — THE exact worst
    requests behind a bad pct99, not an aggregate."""
    d = os.path.join(store_dir, "reqlog")
    if not os.path.isdir(d):
        return []
    from tenzing_tpu.serve.reqlog import read_exemplars, read_request_log

    lines: List[str] = []
    try:
        data = read_request_log(d)
    except OSError:
        return [f"- request log `{d}`: unreadable", ""]
    lines.append(
        f"- request log `{d}`: {len(data['records'])} record(s) across "
        f"{data['segments']} segment(s), {data['dropped_sampling']} "
        f"sampled out" +
        (f"; damage: {data['damaged']} segment(s), "
         f"{data['checksum_failed']} bad checksum(s), "
         f"{data['torn_lines']} torn line(s)"
         if data["damaged"] else ""))
    exemplars = read_exemplars(os.path.join(d, "exemplars"))
    if exemplars:
        # run the worst requests through the causal analyzer so the
        # table says WHERE each one's time went, not just how much
        # (obs/causal.py; ISSUE 16's point of keeping exemplars at all)
        from tenzing_tpu.obs.causal import analyze_bundles

        chains: Dict[str, str] = {}
        paths = [ex["path"] for ex in exemplars[:12] if ex.get("path")]
        if paths:
            try:
                for tid, t in analyze_bundles(paths).items():
                    segs = t.get("segments_us") or {}
                    if segs:
                        top = sorted(segs.items(), key=lambda kv: -kv[1])
                        chains[tid] = ", ".join(
                            f"{k} {v:.0f}" for k, v in top[:3])
            except (OSError, ValueError):
                pass
        lines += ["", "| exemplar (worst requests) | reason | tier | "
                  "resolve (us) | top segments (us) |",
                  "|---|---|---|---|---|"]
        for ex in exemplars[:12]:
            rec = ex.get("record") or {}
            tid = str(ex.get("trace_id", "?"))
            lines.append(
                f"| `{tid[:16]}` | "
                f"{ex.get('reason', '?')} | {rec.get('tier', '—')} | "
                f"{rec.get('resolve_us', '—')} | "
                f"{chains.get(tid, '—')} |")
    lines.append("")
    return lines


def queue_section(queue_dir: str) -> List[str]:
    """The drain-daemon view of one work queue (docs/serving.md "Drain
    daemon"): depth by reason, the torn set (visible rot), live leases
    with heartbeat staleness, the poison quarantine, each worker's
    status JSON, and per-item drain economics mined from the status
    histories + the ``ckpt-*`` checkpoint journals."""
    import time as _time

    from tenzing_tpu.serve.store import WorkQueue

    q = WorkQueue(queue_dir)
    items = q.items()
    by_reason: Dict[str, int] = {}
    for _, payload in items:
        r = payload.get("reason", "?")
        by_reason[r] = by_reason.get(r, 0) + 1
    lines = [f"### work queue `{queue_dir}`", "",
             f"- depth: {len(items)}" +
             (" (" + ", ".join(f"{k}={v}" for k, v in
                               sorted(by_reason.items())) + ")"
              if by_reason else "")]
    if q.torn_paths:
        lines.append(
            f"- torn items: {len(q.torn_paths)} (" +
            ", ".join(f"`{os.path.basename(p)}`"
                      for p in q.torn_paths) + ")")
    leases = q.leases()
    if leases:
        lines += ["", "| lease | owner | heartbeat age (s) |", "|---|---|---|"]
        for l in leases:
            lines.append(f"| `{l['exact'][:12]}` | {l.get('owner', '?')} | "
                         f"{l['age_s']:.1f} |")
    poisoned = q.poisoned()
    if poisoned:
        lines += ["", "| poisoned | reason | attempts | last failure |",
                  "|---|---|---|---|"]
        for path, doc in poisoned:
            atts = doc.get("attempts", [])
            last = atts[-1] if atts else {}
            lines.append(
                f"| `{doc.get('exact', os.path.basename(path))[:12]}` | "
                f"{doc.get('reason', '?')} | {len(atts)} | "
                f"{last.get('error_class', '—')}: "
                f"{(last.get('message') or '—')[:60]} |")
    # daemon status documents: liveness + per-item drain economics
    now = _time.time()
    for name in sorted(os.listdir(queue_dir)):
        if not (name.startswith("status-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(queue_dir, name)) as f:
                st = json.load(f)
        except (OSError, ValueError):
            lines += ["", f"- daemon `{name}`: unreadable"]
            continue
        c = st.get("counters", {})
        stale = now - float(st.get("heartbeat_at", 0))
        lines += ["",
                  f"- daemon `{st.get('owner', name)}`: {st.get('state')}"
                  f", heartbeat {stale:.1f}s ago — claimed "
                  f"{c.get('claimed', 0)}, completed {c.get('completed', 0)}"
                  f", retried {c.get('retried', 0)}, poisoned "
                  f"{c.get('poisoned', 0)}, reclaimed "
                  f"{c.get('reclaimed', 0)}"]
        hist = st.get("history", [])
        if hist:
            lines += ["",
                      "| item | outcome | wall (s) | attempts | "
                      "journal replays | merged |", "|---|---|---|---|---|---|"]
            for h in hist:
                lines.append(
                    f"| `{h.get('exact', '?')[:12]}` | {h.get('outcome')} | "
                    f"{h.get('wall_s', 0):.1f} | {h.get('attempts', 1)} | "
                    f"{h.get('journal_lines_prior', 0)} | "
                    f"{h.get('merged', 0)} |")
    # per-item checkpoint journals: what a re-drain would replay free
    ckpts = sorted(n for n in os.listdir(queue_dir)
                   if n.startswith("ckpt-")
                   and os.path.isdir(os.path.join(queue_dir, n)))
    econ = []
    for n in ckpts:
        jpath = os.path.join(queue_dir, n, "measurements.jsonl")
        meas = batches = 0
        if os.path.exists(jpath):
            with open(jpath) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        j = json.loads(line)
                    except ValueError:
                        continue  # torn tail line
                    if "batch" in j:
                        batches += 1
                    else:
                        meas += 1
        econ.append(f"`{n[5:17]}`: {meas} measurement(s), "
                    f"{batches} batch(es)")
    if econ:
        lines += ["", "- checkpoint journals: " + "; ".join(econ)]
    lines.append("")
    return lines


# -- live fleet view (--follow) ---------------------------------------------

def _age(doc: Dict[str, Any], key: str, now: float) -> str:
    try:
        return f"{now - float(doc.get(key, 0)):.1f}s"
    except (TypeError, ValueError):
        return "?"


def _slo_line(slo: Dict[str, Any]) -> str:
    pct99 = slo.get("pct99_us")
    bits = [f"{slo.get('histogram', '?')} pct99 "
            f"{'—' if pct99 is None else f'{pct99:.1f}us'}"]
    if slo.get("target_us") is not None:
        mark = ("OK" if slo.get("within_target")
                else "MISS" if slo.get("within_target") is False else "?")
        bits.append(f"target {slo['target_us']:.0f}us [{mark}]")
    if slo.get("baseline_pct99_us"):
        bits.append(f"burn {slo.get('burn', '?')} "
                    f"(x{slo.get('vs_baseline', '?')} vs baseline "
                    f"{slo['baseline_pct99_us']:.1f}us)")
    return ", ".join(bits)


def dr_lines(store_dir: str, now: float) -> List[str]:
    """The disaster-recovery posture of one store directory
    (docs/robustness.md "Disaster recovery"): the last ``serve fsck
    --stamp`` verdict and the backup-generation census — rendered so a
    follow screen answers "when did anyone last prove this store clean,
    and how far back could we restore?" without running either tool."""
    from tenzing_tpu.serve import dr

    lines: List[str] = []
    stamps = [os.path.join(store_dir, dr.FSCK_STAMP)]
    stamps += sorted(_glob.glob(os.path.join(store_dir, "*.fsck.json")))
    for sp in stamps:
        try:
            with open(sp) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("kind") != "fsck":
            continue
        lines.append(
            f"fsck   {doc.get('store', store_dir)}: "
            f"{'clean' if doc.get('ok') else 'DAMAGED'} (rc "
            f"{doc.get('rc', '?')}), {doc.get('records', 0)} record(s), "
            f"{len(doc.get('errors') or [])} error(s) / "
            f"{len(doc.get('warnings') or [])} warning(s), stamped "
            f"{_age(doc, 'checked_at', now)} ago")
    root = dr.backups_root(store_dir)
    try:
        gens = dr.list_generations(root)
    except OSError:
        gens = []
    if gens:
        latest = os.path.join(root, gens[-1])
        try:
            cat = dr.load_catalog(latest)
            detail = (f"{len(cat.get('files') or [])} file(s), "
                      f"{_age(cat, 'created_at', now)} ago")
        except dr.DrError as e:
            detail = f"catalog unreadable: {e}"
        lines.append(
            f"backup {store_dir}: {len(gens)} generation(s), latest "
            f"`{os.path.basename(latest)}` ({detail})")
    return lines


def fleet_lines(store_dirs: List[str],
                queue_dirs: List[str]) -> List[str]:
    """One render of the live fleet (docs/observability.md "Fleet
    telemetry plane"): serve-loop and daemon status documents joined
    with their latest metric snapshots — per-process liveness, queue
    depth/age, tier hit mix, SLO state.  Pure reads: follow never
    mutates the tree it watches."""
    import time as _time

    from tenzing_tpu.obs.metrics import latest_snapshots
    from tenzing_tpu.serve.store import WorkQueue

    now = _time.time()
    lines = [f"# fleet @ {_time.strftime('%H:%M:%S')}", ""]
    for d in store_dirs:
        if not os.path.isdir(d):
            continue
        snaps = latest_snapshots(d)
        for name in sorted(os.listdir(d)):
            if not (name.startswith("status-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    st = json.load(f)
            except (OSError, ValueError):
                lines.append(f"serve  {name}: unreadable")
                continue
            if st.get("kind") != "serve_loop":
                continue
            c = st.get("counters", {})
            served = {t: c.get(f"served_{t}", 0)
                      for t in ("exact", "near", "cold")}
            total = sum(served.values()) or 1
            mix = "/".join(f"{t}:{n} ({100 * n // total}%)"
                           for t, n in served.items())
            ro = st.get("store_readonly")
            lines.append(
                f"serve  {st.get('owner', name)}: {st.get('state')}"
                + (" [STORE READONLY — exact only, near/cold shed]"
                   if ro else "")
                + f", hb {_age(st, 'heartbeat_at', now)} ago, queue "
                f"{st.get('queue_depth', 0)} (+{st.get('in_flight', 0)} "
                f"in flight), shed {c.get('shed', 0)}, timeouts "
                f"{c.get('timeouts', 0)}, mix {mix}")
            snap = snaps.get(st.get("owner", ""))
            if snap:
                gauges = (snap.get("metrics") or {}).get("gauges", {})
                tr = snap.get("tracer") or {}
                extras = [f"queue age {gauges.get('serve.queue_age_s', 0)}s",
                          f"shed rate {gauges.get('serve.shed_rate', 0)}/s"]
                if snap.get("uptime_s") is not None:
                    extras.append(f"up {snap['uptime_s']}s")
                if tr.get("dropped_spans") or tr.get("dropped_events"):
                    extras.append(
                        f"tracer dropped {tr.get('dropped_spans', 0)}sp/"
                        f"{tr.get('dropped_events', 0)}ev")
                lines.append(f"       {', '.join(extras)}")
                if snap.get("slo"):
                    lines.append(f"       slo: {_slo_line(snap['slo'])}")
                rl = snap.get("reqlog")
                if rl:
                    # the traffic recorder's own position (serve/
                    # reqlog.py): the watchtower is observable too
                    lines.append(
                        f"       reqlog: {rl.get('records', 0)} rec / "
                        f"{rl.get('segments', 0)} seg "
                        f"({rl.get('bytes', 0)}B, "
                        f"{rl.get('buffered', 0)} buffered, "
                        f"{rl.get('dropped_sampling', 0)} sampled out)")
        # disaster-recovery posture: last fsck verdict + backup census
        lines += dr_lines(d, now)
    for qd in queue_dirs:
        if not os.path.isdir(qd):
            lines.append(f"queue  {qd}: missing directory")
            continue
        q = WorkQueue(qd)
        items = q.items()
        ages = []
        for p, _ in items:
            try:
                ages.append(now - os.path.getmtime(p))
            except OSError:
                pass
        leases = q.leases()
        lines.append(
            f"queue  {qd}: depth {len(items)}"
            + (f", oldest {max(ages):.1f}s" if ages else "")
            + (f", torn {len(q.torn_paths)}" if q.torn_paths else "")
            + f", leases {len(leases)}"
            + (f" (max hb age {max(l['age_s'] for l in leases):.1f}s)"
               if leases else "")
            + f", poisoned {len(q.poisoned())}")
        snaps = latest_snapshots(qd)
        for name in sorted(os.listdir(qd)):
            if not (name.startswith("status-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(qd, name)) as f:
                    st = json.load(f)
            except (OSError, ValueError):
                lines.append(f"daemon {name}: unreadable")
                continue
            if st.get("kind") == "serve_loop":
                continue  # a listen loop pointed at the queue dir
            if st.get("kind") == "supervisor":
                # the fleet controller (serve/supervisor.py): members,
                # scaling verdict, and any open crash-loop breakers
                sc = st.get("scaling") or {}
                lines.append(
                    f"superv {st.get('owner', name)}: "
                    f"{st.get('state')}, hb "
                    f"{_age(st, 'heartbeat_at', now)} ago, members "
                    f"{st.get('n_members', 0)} (desired "
                    f"{sc.get('desired', st.get('desired_n', '?'))})"
                    + (", scale-up suppressed (poison)"
                       if sc.get("suppressed_poison") else ""))
                for mb in st.get("members") or []:
                    lines.append(
                        f"       member {mb.get('owner')}: "
                        f"{mb.get('state')}"
                        + (" (adopted)" if mb.get("adopted") else "")
                        + (f", {mb.get('restarts')} restart(s)"
                           if mb.get("restarts") else ""))
                for owner, b in sorted(
                        (st.get("breakers") or {}).items()):
                    lines.append(
                        f"       breaker {owner}: {b.get('state')} "
                        f"({b.get('restarts_in_window')}/"
                        f"{b.get('max_restarts')} restarts in "
                        f"{b.get('window_s')}s)")
                continue
            c = st.get("counters", {})
            item = st.get("item") or {}
            lines.append(
                f"daemon {st.get('owner', name)}: {st.get('state')}"
                + (" [STORE READONLY — claims paused]"
                   if st.get("store_readonly") else "")
                + f", hb {_age(st, 'heartbeat_at', now)} ago, claimed "
                f"{c.get('claimed', 0)}, completed {c.get('completed', 0)}"
                f", retried {c.get('retried', 0)}, poisoned "
                f"{c.get('poisoned', 0)}"
                + (f", draining {str(item.get('exact', ''))[:12]} "
                   f"({now - float(item.get('since', now)):.0f}s)"
                   if item else ""))
            snap = snaps.get(st.get("owner", ""))
            if snap:
                gauges = (snap.get("metrics") or {}).get("gauges", {})
                lines.append(
                    f"       item age "
                    f"{gauges.get('daemon.item_age_s', 0)}s, lease age "
                    f"{gauges.get('daemon.lease_age_s', 0)}s")
    # arrival-vs-drain backlog economics (obs/alerts.py): the always-on
    # fleet-sizing line the queue_backlog_burn rule fires from
    from tenzing_tpu.obs.alerts import backlog_summary, firing_lines

    bl = backlog_summary(store_dirs, queue_dirs)
    if bl.get("depth") or bl.get("arrival_per_s"):
        lines.append(
            f"burn   arrival {bl['arrival_per_s']:.2f}/s vs drain "
            f"{bl['drain_per_s']:.2f}/s ({bl['daemons']} daemon(s)), "
            f"depth {bl['depth']}, recommended fleet "
            f"{bl['recommended_daemons']}")
    # worst recent exemplar through the causal analyzer: one line of
    # where the tail's time went, refreshed every tick (obs/causal.py)
    for d in store_dirs:
        ex_dir = os.path.join(d, "reqlog", "exemplars")
        if not os.path.isdir(ex_dir):
            continue
        from tenzing_tpu.obs.causal import analyze_bundles
        from tenzing_tpu.serve.reqlog import read_exemplars

        try:
            exemplars = read_exemplars(ex_dir)[:4]
            paths = [ex["path"] for ex in exemplars if ex.get("path")]
            traces = analyze_bundles(paths) if paths else {}
        except (OSError, ValueError):
            continue
        good = [t for t in traces.values() if t.get("segments_us")]
        if good:
            worst = max(good, key=lambda t: t["window_us"])
            top = sorted(worst["segments_us"].items(),
                         key=lambda kv: -kv[1])[:3]
            lines.append(
                f"causal {worst['trace_id'][:16]}: "
                f"{worst['window_us']:.0f}us window, "
                + ", ".join(f"{k} {v:.0f}us" for k, v in top)
                + f", coverage {worst['coverage']:.0%}")

    lines += firing_lines(store_dirs, queue_dirs)
    for d in dict.fromkeys(store_dirs + queue_dirs):
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not (name.startswith("alerts-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            firing = doc.get("firing", [])
            lines.append(
                f"ledger {name}: {len(firing)} firing, updated "
                f"{_age(doc, 'updated_at', now)} ago"
                + (f" ({', '.join(firing[:4])}"
                   + (", ..." if len(firing) > 4 else "") + ")"
                   if firing else ""))
    if len(lines) <= 2:
        lines.append("(no status documents found)")
    lines.append("")
    return lines


def causal_section(bundle_paths: List[str]) -> List[str]:
    """The causal-observatory section (obs/causal.py,
    docs/observability.md "Causal analysis"): per-trace critical-path
    chains over telemetry bundles plus the fleet-wide "where the pct99
    lives" rollup."""
    from tenzing_tpu.obs.causal import aggregate, analyze_bundles

    traces = analyze_bundles(bundle_paths)
    lines = ["## Causal analysis", "",
             f"- bundles: {len(bundle_paths)}, traces: {len(traces)}"]
    good = sorted((t for t in traces.values() if "error" not in t),
                  key=lambda t: -t["window_us"])
    if good:
        lines += ["", "| trace | tier | window (us) | queue wait (us) | "
                  "coverage | chain |", "|---|---|---|---|---|---|"]
        for t in good[:12]:
            chain = " > ".join(
                c["segment"] for c in t["chain"]
                if c["segment"] != "unattributed")
            lines.append(
                f"| `{t['trace_id'][:16]}` | {t['tier']} | "
                f"{t['window_us']:.0f} | {t['queue_wait_us']:.0f} | "
                f"{t['coverage']:.0%} | {chain} |")
        agg = aggregate(traces)
        rank = agg.get("pct99_ranking") or []
        if rank:
            lines += ["", "where the pct99 lives (tail traces, "
                      f"window >= {agg['pct99_window_us']:.0f}us):"]
            for r in rank[:6]:
                lines.append(f"- {r['segment']}: {r['sum_us']:.0f}us "
                             f"({r['share']:.0%})")
        dec = agg.get("decomposition") or {}
        if dec:
            qw, sv = dec["queue_wait_us"], dec["service_us"]
            lines.append(
                f"- queue wait vs service p99: {qw['p99_us']:.0f}us vs "
                f"{sv['p99_us']:.0f}us")
    lines.append("")
    return lines


def follow(store_dirs: List[str], queue_dirs: List[str],
           interval: float = 2.0, max_ticks: Optional[int] = None,
           out=None) -> int:
    """Render :func:`fleet_lines` every ``interval`` seconds until
    Ctrl-C (or ``max_ticks`` renders — the CI/test bound)."""
    out = out if out is not None else sys.stdout
    ticks = 0
    try:
        while True:
            out.write("\n".join(fleet_lines(store_dirs, queue_dirs)) + "\n")
            out.flush()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


# -- CLI --------------------------------------------------------------------

def _expand(globs: Optional[List[str]]) -> List[str]:
    out: List[str] = []
    for pat in globs or []:
        hits = sorted(_glob.glob(pat))
        out.extend(hits if hits else ([pat] if os.path.exists(pat) else []))
    return out


def build_report(args) -> Tuple[str, Optional[Dict[str, Any]]]:
    lines: List[str] = ["# tenzing-tpu corpus report", ""]
    verdict: Optional[Dict[str, Any]] = None
    csvs = _expand(args.csv)
    if csvs:
        lines += corpus_section(csvs)
    benches = _expand(args.bench)
    if benches:
        lines += bench_section(benches)
    if args.journal:
        lines += journal_section(args.journal)
    traces = _expand(args.trace)
    if traces:
        lines += trace_section(traces)
    metrics = _expand(args.metrics)
    if metrics:
        lines += metrics_section(metrics)
    stores = _expand(args.store)
    if stores or args.queue_dir:
        lines += store_section(stores, queue_dir=args.queue_dir)
    causal_globs = _expand(getattr(args, "causal", None))
    if causal_globs:
        lines += causal_section(causal_globs)
    if args.check:
        fresh = _load_check_doc(args.check)
        baseline = _load_check_doc(args.baseline)
        f_serve = fresh.get("kind") == "serve_trace_replay"
        b_serve = baseline.get("kind") == "serve_trace_replay"
        if f_serve != b_serve:
            # a mixed pair means a mis-wired gate (e.g. a BENCH baseline
            # against a SERVE_BENCH fresh): every extraction would come
            # back None and the check would vacuously pass — fail the
            # wiring loudly instead (exit 2, usage error)
            raise ValueError(
                f"regression-check family mismatch: {args.check} is "
                f"{'serve-replay' if f_serve else 'driver'}-family but "
                f"{args.baseline} is "
                f"{'serve-replay' if b_serve else 'driver'}-family")
        if f_serve:
            # the SERVE_BENCH family gates on serving latency, not
            # search quality — same CLI, same exit-code contract
            verdict = check_serve_regression(fresh, baseline, tol=args.tol)
        else:
            verdict = check_regression(fresh, baseline, tol=args.tol)
        lines += ["## Regression check", "",
                  f"- fresh: `{args.check}`",
                  f"- baseline: `{args.baseline}` (tol {args.tol:.0%})",
                  f"- **verdict: {verdict['verdict']}**"]
        for r in verdict["reasons"]:
            lines.append(f"  - {r}")
        fvt = verdict["checks"].get("host_noise")
        if isinstance(fvt, dict) and fvt.get("line"):
            # the measured floor-vs-tail read (obs/noise.py): is the
            # residual tail the host's fault or the serving path's?
            lines.append(f"- {fvt['line']}")
        lines += ["", "```json",
                  json.dumps(verdict["checks"], indent=2, sort_keys=True),
                  "```", ""]
    if len(lines) <= 2:
        lines += ["(no inputs given — see --help)", ""]
    return "\n".join(lines), verdict


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.obs.report",
        description="Mine the measurement corpus into a markdown report "
                    "and run the noise-aware regression check "
                    "(docs/observability.md, 'Attribution').")
    ap.add_argument("--csv", nargs="*", default=None, metavar="GLOB",
                    help="recorded search databases (bench.py --dump-csv)")
    ap.add_argument("--bench", nargs="*", default=None, metavar="GLOB",
                    help="driver JSON verdicts (raw lines or BENCH_*.json "
                         "wrappers)")
    ap.add_argument("--journal", nargs="*", default=None, metavar="DIR",
                    help="checkpoint directories (bench.py --checkpoint)")
    ap.add_argument("--trace", nargs="*", default=None, metavar="GLOB",
                    help="telemetry JSONL bundles (bench.py --trace-out)")
    ap.add_argument("--metrics", nargs="*", default=None, metavar="GLOB",
                    help="metrics JSON files (bench.py --metrics-json)")
    ap.add_argument("--store", nargs="*", default=None, metavar="PATH",
                    help="schedule-serving store files "
                         "(python -m tenzing_tpu.serve, docs/serving.md)")
    ap.add_argument("--queue-dir", default=None, metavar="DIR",
                    help="serving work-queue directory (cold/refinement "
                         "depth by reason)")
    ap.add_argument("--causal", nargs="*", default=None, metavar="GLOB",
                    help="telemetry bundles for the per-request "
                         "critical-path section (obs/causal.py)")
    ap.add_argument("--check", default=None, metavar="FRESH",
                    help="fresh driver JSON for the regression check")
    ap.add_argument("--baseline", default=None, metavar="BASE",
                    help="committed baseline driver JSON (e.g. "
                         "BENCH_r05.json)")
    ap.add_argument("--tol", type=float, default=0.05,
                    help="relative regression tolerance (default 0.05)")
    ap.add_argument("--out", default=None,
                    help="write the markdown report here (default stdout)")
    ap.add_argument("--follow", action="store_true",
                    help="live fleet view: tail status + metric-snapshot "
                         "documents under --store / --queue-dir "
                         "(docs/observability.md)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="SECS",
                    help="--follow refresh interval")
    ap.add_argument("--max-ticks", type=int, default=None,
                    help="--follow: stop after N renders (CI/tests)")
    args = ap.parse_args(argv)
    if bool(args.check) != bool(args.baseline):
        ap.error("--check and --baseline must be given together")
    if args.follow:
        store_dirs = []
        for p in args.store or []:
            if os.path.isdir(p):
                store_dirs.append(p)
            elif p.endswith(".json"):
                # a monolithic store: its status docs live beside it
                store_dirs.append(os.path.dirname(os.path.abspath(p)))
        if not store_dirs and not args.queue_dir:
            ap.error("--follow needs --store and/or --queue-dir")
        return follow(store_dirs,
                      [args.queue_dir] if args.queue_dir else [],
                      interval=args.interval, max_ticks=args.max_ticks)
    try:
        report, verdict = build_report(args)
    except (OSError, ValueError) as e:
        sys.stderr.write(f"report: {e}\n")
        return 2
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
        sys.stderr.write(f"report: {args.out}\n")
    else:
        sys.stdout.write(report)
    return 1 if (verdict and verdict["verdict"] == "regression") else 0


if __name__ == "__main__":
    sys.exit(main())
