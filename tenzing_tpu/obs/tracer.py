"""Span/event tracer: the structured replacement for ad-hoc prints and timers.

A :class:`Tracer` records two kinds of things:

* **spans** — named intervals with attributes, nested per thread (a span
  opened inside another span records it as its parent), opened with the
  ``with tracer.span("mcts.iter", it=3) as sp:`` context manager; attributes
  can be added while the span is open (``sp.set("pct50", t)``);
* **events** — named instants with attributes (``tracer.event("bench.cache",
  hit=True)``).

Records are tagged with a ``pid`` (the control plane rank — set by
``parallel/control_plane.py`` so multi-host traces merge into one Perfetto
timeline, one process row per rank) and a ``tid`` (a dense per-thread index).
Timestamps are unix-epoch microseconds derived from one ``perf_counter``
anchor per tracer, so intervals are monotonic within a rank and roughly
NTP-aligned across ranks.

**Disabled is the default and costs almost nothing**: the module-global
tracer starts disabled, and a disabled ``span()`` / ``event()`` returns a
shared no-op immediately — no allocation, no locking, no timestamp (the
contract tests/test_obs.py::test_disabled_tracer_is_noop relies on).  Enable
it process-wide with :func:`configure` (what ``bench.py --trace-out`` does).

While a cross-process trace context is ambient (obs/context.py — minted
at serve-listen ingress, adopted by drain daemons and their children),
every recorded span and event is additionally stamped with ``trace_id``
/ ``parent_span`` attrs, so bundles from different fleet processes
stitch into one request journey (obs/export.py ``stitch``).

**Retention is bounded**: a long-lived process (``serve listen``, the
drain daemon) records forever, so the span/event buffers are rings —
beyond ``max_spans`` / ``max_events`` the OLDEST records are evicted
(the tail is what a live dashboard and a post-mortem read) and
``dropped_spans`` / ``dropped_events`` count what fell off, surfaced in
metric snapshots so silent loss is impossible.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

from tenzing_tpu.obs.context import current_trace_attrs

# the default span/event ring bounds: generous enough that every search
# bundle to date fits untruncated, small enough that a multi-hour serve
# loop stays O(100 MB) worst-case instead of unbounded
MAX_SPANS = 200_000
MAX_EVENTS = 200_000


def short_digest(payload: str) -> str:
    """12-hex sha1 of a serialized payload — THE schedule-id convention
    every telemetry emitter shares (bench.benchmark spans, executor.compile
    spans, bench.cache events), so trace records for the same schedule
    correlate byte-for-byte across subsystems and hosts."""
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


class Span:
    """One finished-or-open interval.  ``ts_us``/``dur_us`` are unix-epoch
    microseconds; ``attrs`` is a plain JSON-safe dict."""

    __slots__ = ("name", "ts_us", "dur_us", "pid", "tid", "span_id",
                 "parent_id", "attrs")

    def __init__(self, name: str, ts_us: float, pid: int, tid: int,
                 span_id: int, parent_id: Optional[int],
                 attrs: Dict[str, Any]):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = 0.0
        self.pid = pid
        self.tid = tid
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs

    def set(self, key: str, value: Any) -> None:
        """Attach/overwrite one attribute (usable while the span is open)."""
        self.attrs[key] = value

    def to_json(self) -> Dict[str, Any]:
        return {
            "kind": "span",
            "name": self.name,
            "ts_us": self.ts_us,
            "dur_us": self.dur_us,
            "pid": self.pid,
            "tid": self.tid,
            "id": self.span_id,
            "parent": self.parent_id,
            "attrs": self.attrs,
        }


class Event:
    """One instant with attributes."""

    __slots__ = ("name", "ts_us", "pid", "tid", "attrs")

    def __init__(self, name: str, ts_us: float, pid: int, tid: int,
                 attrs: Dict[str, Any]):
        self.name = name
        self.ts_us = ts_us
        self.pid = pid
        self.tid = tid
        self.attrs = attrs

    def to_json(self) -> Dict[str, Any]:
        return {
            "kind": "event",
            "name": self.name,
            "ts_us": self.ts_us,
            "pid": self.pid,
            "tid": self.tid,
            "attrs": self.attrs,
        }


class _NullSpan:
    """The span handed out when tracing is disabled: every method a no-op."""

    __slots__ = ()

    def set(self, key: str, value: Any) -> None:
        return None


class _NullSpanCtx:
    """Reusable no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return _NULL_SPAN

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()
_NULL_CTX = _NullSpanCtx()


class Tracer:
    """Thread-safe span/event recorder (see module docstring)."""

    def __init__(self, enabled: bool = True, rank: int = 0,
                 max_spans: int = MAX_SPANS, max_events: int = MAX_EVENTS):
        self.enabled = enabled
        self.rank = rank
        self._lock = threading.Lock()
        # bounded rings (module docstring): a full ring evicts oldest
        # and counts the drop — a serve loop cannot grow without bound
        self._spans: Deque[Span] = deque(maxlen=max(1, max_spans))
        self._events: Deque[Event] = deque(maxlen=max(1, max_events))
        self.dropped_spans = 0
        self.dropped_events = 0
        self._listeners: List[Callable[[str, Any], None]] = []
        self._local = threading.local()
        self._tids: Dict[int, int] = {}
        self._next_tid = 0
        # live per-thread open-span stacks, keyed by thread ident: the
        # export-time flush (ISSUE 3 satellite) reads OTHER threads' stacks
        # to close in-flight spans, so the stacks must be reachable beyond
        # the owning thread's threading.local view
        self._open_stacks: Dict[int, List[Span]] = {}
        self._next_span_id = 0
        # one perf_counter anchor -> monotonic unix-us timestamps
        self._t0_unix = time.time()
        self._t0_perf = time.perf_counter()

    # -- plumbing ----------------------------------------------------------
    def _now_us(self) -> float:
        return (self._t0_unix + (time.perf_counter() - self._t0_perf)) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    # a monotonic counter, not len(): dead-thread idents
                    # are pruned at snapshot time (a socket serve loop
                    # spawns one reader thread per connection, forever),
                    # and a pruned-then-reused index would merge two
                    # different threads' tracks
                    tid = self._tids[ident] = self._next_tid
                    self._next_tid += 1
        return tid

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
            with self._lock:
                self._open_stacks[threading.get_ident()] = stack
        return stack

    def set_rank(self, rank: int) -> None:
        """Tag subsequent records with this control-plane rank (pid)."""
        self.rank = int(rank)

    def add_listener(self, fn: Callable[[str, Any], None]) -> None:
        """``fn(kind, record)`` called on every finished span ("span") and
        emitted event ("event") while the tracer is enabled."""
        self._listeners.append(fn)

    def _notify(self, kind: str, record: Any) -> None:
        for fn in self._listeners:
            try:
                fn(kind, record)
            except Exception:
                pass  # a broken listener must not take down the search

    # -- recording ---------------------------------------------------------
    def span(self, name: str, **attrs: Any):
        """Context manager opening a nested span; yields the :class:`Span`."""
        if not self.enabled:
            return _NULL_CTX
        return self._span_ctx(name, attrs)

    @contextmanager
    def _span_ctx(self, name: str, attrs: Dict[str, Any]) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        trace = current_trace_attrs()
        if trace is not None:
            # stamp the ambient cross-process context (obs/context.py);
            # explicit attrs win, and nested spans need no parent_span —
            # their in-process parent chain already resolves
            if parent is not None:
                trace = {"trace_id": trace["trace_id"]}
            attrs = {**trace, **attrs}
        with self._lock:
            span_id = self._next_span_id
            self._next_span_id += 1
        sp = Span(name, self._now_us(), self.rank, self._tid(), span_id,
                  parent, attrs)
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.dur_us = self._now_us() - sp.ts_us
            stack.pop()
            with self._lock:
                if len(self._spans) == self._spans.maxlen:
                    self.dropped_spans += 1  # ring full: oldest evicts
                self._spans.append(sp)
            self._notify("span", sp)

    def event(self, name: str, **attrs: Any) -> None:
        """Record one instant event."""
        if not self.enabled:
            return
        trace = current_trace_attrs()
        if trace is not None:
            attrs = {"trace_id": trace["trace_id"], **attrs}
        ev = Event(name, self._now_us(), self.rank, self._tid(), attrs)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped_events += 1
            self._events.append(ev)
        self._notify("event", ev)

    # -- reading -----------------------------------------------------------
    def spans(self) -> List[Span]:
        """Snapshot of finished spans (completion order)."""
        with self._lock:
            return list(self._spans)

    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    def snapshot(self, block: bool = True, flush_open: bool = True):
        """(finished spans, events, flushed open spans) — THE export-time
        read (obs/export.py).

        ``block=False`` makes the read **async-signal-safe**: the lock is
        taken with ``blocking=False`` and, when it cannot be acquired (the
        interrupted thread may hold it — the Ctrl-C + ``--trace-out``
        deadlock this replaces), the lists are copied without it.  A bare
        ``list(x)`` of a list is atomic under the GIL, so the fallback
        yields a consistent prefix rather than a crash or a hang.

        ``flush_open`` closes a *copy* of every in-flight span (all
        threads) with duration up-to-now and a ``flushed: true`` attribute:
        an interrupted run's bundle keeps its open ``mcts.iter`` /
        ``bench.benchmark`` spans, and no exported record references a
        parent id that never exports (the dangling-parent gap)."""
        acquired = self._lock.acquire(blocking=block)
        try:
            # stacks first: a span closing concurrently then shows up in
            # both copies (filtered by span id below), never in neither
            stacks = [list(s) for s in list(self._open_stacks.values())]
            spans = list(self._spans)
            events = list(self._events)
            if acquired:
                # retention housekeeping (safe only under the real lock):
                # threads die but their ident keys do not — a socket serve
                # loop makes one reader thread per connection, so the
                # stack/tid maps of DEAD threads with nothing in flight
                # are pruned here, the one periodic read every long-lived
                # process already performs
                live = {t.ident for t in threading.enumerate()}
                for ident in [i for i, s in self._open_stacks.items()
                              if not s and i not in live]:
                    del self._open_stacks[ident]
                    self._tids.pop(ident, None)
        finally:
            if acquired:
                self._lock.release()
        open_spans: List[Span] = []
        if flush_open:
            now = self._now_us()
            done_ids = {s.span_id for s in spans}
            for stack in stacks:
                for sp in stack:
                    if sp.span_id in done_ids:
                        continue
                    cp = Span(sp.name, sp.ts_us, sp.pid, sp.tid, sp.span_id,
                              sp.parent_id, dict(sp.attrs))
                    cp.dur_us = max(0.0, now - sp.ts_us)
                    cp.attrs["flushed"] = True
                    open_spans.append(cp)
        return spans, events, open_spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._events.clear()
            self.dropped_spans = 0
            self.dropped_events = 0

    def retention(self) -> Dict[str, int]:
        """Buffer occupancy + drop counts — what metric snapshots carry
        so ring eviction in a long-lived process is visible, never
        silent (obs/metrics.py ``MetricsSnapshotWriter``)."""
        return {
            "spans": len(self._spans),
            "events": len(self._events),
            "max_spans": self._spans.maxlen or 0,
            "max_events": self._events.maxlen or 0,
            "dropped_spans": self.dropped_spans,
            "dropped_events": self.dropped_events,
        }


# -- process-global tracer -------------------------------------------------

_GLOBAL = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process-global tracer (disabled until :func:`configure`)."""
    return _GLOBAL


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (tests); returns the previous one."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, tracer
    return prev


def configure(enabled: bool = True, rank: Optional[int] = None) -> Tracer:
    """Enable/disable the global tracer in place (records are kept)."""
    _GLOBAL.enabled = enabled
    if rank is not None:
        _GLOBAL.set_rank(rank)
    return _GLOBAL
