"""Telemetry sinks: JSONL for machines, Chrome trace-event JSON for Perfetto.

Two serializations of one tracer's records:

* **JSONL** — one JSON object per line (``kind`` = "span" | "event"), in
  timestamp order: the archival format downstream tooling greps / re-derives
  statistics from (the "search telemetry is training data" direction of
  arXiv:2203.02530).  Round-trips through :func:`read_jsonl`.
* **Chrome trace-event JSON** — the ``{"traceEvents": [...]}`` format
  Perfetto (https://ui.perfetto.dev) and chrome://tracing load directly:
  spans as complete events (``ph: "X"`` with ``ts``/``dur``), events as
  thread-scoped instants (``ph: "i"``), plus ``ph: "M"`` metadata naming
  each pid "rank N" so merged multi-host bundles read as one process row per
  rank.  Field semantics: https://docs.google.com/document/d/1CvAClvFfyA5R-
  PhYUmn5OOQtYMH4h6I0nSsKchNAySU (ts/dur in microseconds).

Multi-host merging: each rank writes its own bundle; concatenating the JSONL
files (or the ``traceEvents`` lists) merges them — records are pid-tagged
with the rank, timestamps are unix-anchored.

**Cross-process stitching** (:func:`stitch`, ``python -m
tenzing_tpu.obs.export``): the fleet telemetry plane's merge step
(docs/observability.md).  Each *process's* JSONL bundle (the listen
loop's, a drain daemon's, a drain child's) becomes its own Perfetto
process row, and records stamped with a ``trace_id`` (obs/context.py)
are tied together with flow arrows — one request's journey from socket
accept through cold-enqueue, subprocess drain, and store merge reads as
one connected line across process tracks.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.obs.tracer import Tracer


def _snapshot(tracer: Tracer):
    """One consistent read of the tracer for both sinks.  Non-blocking
    (``block=False``): export runs from atexit AND from the signal trap
    (bench.py ``write_telemetry``), where the interrupted thread may hold
    the tracer lock — a blocking read there deadlocks the Ctrl-C path.
    ``flush_open`` closes copies of all in-flight spans so an interrupted
    run's bundle keeps them (marked ``flushed: true``) and every exported
    ``parent`` id resolves to an exported span."""
    spans, events, open_spans = tracer.snapshot(block=False, flush_open=True)
    return spans + open_spans, events


def _records(tracer: Tracer) -> List[Dict[str, Any]]:
    spans, events = _snapshot(tracer)
    recs = [s.to_json() for s in spans]
    recs += [e.to_json() for e in events]
    recs.sort(key=lambda r: r["ts_us"])
    return recs


def to_jsonl(tracer: Tracer) -> str:
    """All records, one JSON object per line, timestamp-ordered."""
    return "".join(
        json.dumps(r, sort_keys=True, default=str) + "\n"
        for r in _records(tracer)
    )


def write_jsonl(tracer: Tracer, path: str) -> None:
    with open(path, "w") as f:
        f.write(to_jsonl(tracer))


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL bundle back to record dicts (the round-trip contract)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _category(name: str) -> str:
    """Perfetto category = the subsystem prefix of the record name
    ("mcts.iter" -> "mcts"); names without a dot categorize as themselves."""
    return name.split(".", 1)[0]


def _track_name(tid: int, lanes: Dict[int, int]) -> str:
    """Human name of a thread track: spans that carried a ``lane``
    attribute name their track after the lane; plain threads keep a
    ``thread N`` label (tid 0 — the main thread everywhere in this
    codebase — reads as ``main``)."""
    if tid in lanes:
        return f"lane {lanes[tid]}"
    return "main" if tid == 0 else f"thread {tid}"


def chrome_trace(tracer: Tracer,
                 extra_events: List[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The trace as a Chrome trace-event object (see module docstring).

    Tracks are NAMED (``thread_name`` metadata per (pid, tid), on top of
    the per-rank ``process_name`` rows): a span whose attrs carry a
    ``lane`` names its track ``lane N``, so attribution timelines
    (obs/attrib/explain.py ``timeline_trace_events`` — passed in via
    ``extra_events``, which may carry their own ``M`` metadata) and the
    ordinary spans render as one grouped per-rank trace instead of flat
    anonymous thread rows."""
    trace_events: List[Dict[str, Any]] = []
    pids = set()
    tids = set()  # (pid, tid) pairs needing a thread_name row
    lane_of: Dict[int, int] = {}  # tid -> lane id, when a span declares one
    spans, events = _snapshot(tracer)
    for sp in spans:
        pids.add(sp.pid)
        tids.add((sp.pid, sp.tid))
        lane = sp.attrs.get("lane")
        if isinstance(lane, int):
            lane_of[sp.tid] = lane
        trace_events.append({
            "name": sp.name,
            "cat": _category(sp.name),
            "ph": "X",
            "ts": sp.ts_us,
            "dur": sp.dur_us,
            "pid": sp.pid,
            "tid": sp.tid,
            "args": sp.attrs,
        })
    for ev in events:
        pids.add(ev.pid)
        tids.add((ev.pid, ev.tid))
        trace_events.append({
            "name": ev.name,
            "cat": _category(ev.name),
            "ph": "i",
            "ts": ev.ts_us,
            "pid": ev.pid,
            "tid": ev.tid,
            "s": "t",  # thread-scoped instant
            "args": ev.attrs,
        })
    extra_meta: List[Dict[str, Any]] = []
    for e in extra_events or []:
        if e.get("ph") == "M":
            extra_meta.append(e)
            continue
        pids.add(e.get("pid", 0))
        trace_events.append(e)
    trace_events.sort(key=lambda e: e["ts"])
    meta = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"rank {pid}"}}
        for pid in sorted(pids)
    ]
    meta += [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": _track_name(tid, lane_of)}}
        for pid, tid in sorted(tids)
    ]
    meta += extra_meta
    return {"traceEvents": meta + trace_events, "displayTimeUnit": "ms"}


def write_chrome_trace(tracer: Tracer, path: str,
                       extra_events: List[Dict[str, Any]] = None) -> None:
    """Write the Perfetto-loadable trace; ``extra_events`` appends
    pre-built trace-event dicts (e.g. the attribution profiler's per-lane
    Gantt tracks) into the same bundle."""
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer, extra_events=extra_events), f,
                  default=str)


# -- cross-process trace stitching ------------------------------------------

def stitch_records(
        bundles: List[Tuple[str, List[Dict[str, Any]]]],
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Merge per-process JSONL record lists into one Chrome trace-event
    document (module docstring).  ``bundles`` is ``(label, records)``
    per process — each gets its own Perfetto pid (the in-bundle rank
    pids would collide: every fleet process is its own rank 0).

    Returns ``(chrome_doc, summary)``; the summary indexes every
    ``trace_id`` seen — which processes it touched, which span/event
    names carried it — and is what the CI smoke asserts the
    ingress→drain→store-merge linkage on."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    traces: Dict[str, Dict[str, Any]] = {}
    # (trace_id -> [(ts, pid, tid)]) anchors for the flow arrows
    flow_anchors: Dict[str, List[Tuple[float, int, int]]] = {}
    for pid, (label, recs) in enumerate(bundles):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": label}})
        lane_of: Dict[int, int] = {}
        tids = set()
        for r in recs:
            kind = r.get("kind")
            if kind not in ("span", "event"):
                continue
            attrs = r.get("attrs") or {}
            tid = int(r.get("tid", 0))
            tids.add(tid)
            lane = attrs.get("lane")
            if isinstance(lane, int):
                lane_of[tid] = lane
            ev: Dict[str, Any] = {
                "name": r.get("name", "?"),
                "cat": _category(r.get("name", "?")),
                "ts": r.get("ts_us", 0.0),
                "pid": pid,
                "tid": tid,
                "args": attrs,
            }
            if kind == "span":
                ev["ph"] = "X"
                ev["dur"] = r.get("dur_us", 0.0)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
            tid_trace = attrs.get("trace_id")
            if isinstance(tid_trace, str) and tid_trace:
                t = traces.setdefault(tid_trace, {
                    "processes": set(), "names": set(), "records": 0})
                t["processes"].add(label)
                t["names"].add(r.get("name", "?"))
                t["records"] += 1
                if kind == "span":
                    flow_anchors.setdefault(tid_trace, []).append(
                        (float(r.get("ts_us", 0.0)), pid, tid))
        meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": t,
                  "args": {"name": _track_name(t, lane_of)}}
                 for t in sorted(tids)]
    # flow arrows: one s → t... → f chain per trace, anchored at the
    # start of each span that carried it, in timestamp order — Perfetto
    # draws the request's journey across the process rows
    flows: List[Dict[str, Any]] = []
    for trace_id, anchors in flow_anchors.items():
        anchors.sort()
        if len(anchors) < 2:
            continue
        for i, (ts, pid, tid) in enumerate(anchors):
            ph = "s" if i == 0 else ("f" if i == len(anchors) - 1 else "t")
            flow = {"name": f"trace {trace_id[:8]}", "cat": "trace",
                    "ph": ph, "id": trace_id, "ts": ts, "pid": pid,
                    "tid": tid}
            if ph == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            flows.append(flow)
    events.sort(key=lambda e: e["ts"])
    doc = {"traceEvents": meta + flows + events, "displayTimeUnit": "ms"}
    summary = {
        "bundles": [label for label, _ in bundles],
        "records": sum(len(r) for _, r in bundles),
        "traces": {
            tid: {
                "processes": sorted(t["processes"]),
                "n_processes": len(t["processes"]),
                "names": sorted(t["names"]),
                "records": t["records"],
            }
            for tid, t in sorted(traces.items())
        },
    }
    return doc, summary


def _bundle_labels(paths: List[str]) -> List[str]:
    """Unique human labels: the basename where unique; colliding groups
    grow leading path components until they separate (every drain child
    writes ``ckpt-<exact>/trace/trace.jsonl``, so one parent directory
    is NOT enough — identical labels would merge two processes' rows
    and undercount a trace's process span); pathologically identical
    paths fall back to an index prefix."""

    def suffix(p: str, depth: int) -> str:
        parts = os.path.normpath(p).split(os.sep)
        return "/".join(parts[-depth:] if depth < len(parts) else parts)

    labels = [os.path.basename(p) for p in paths]
    max_depth = max(len(os.path.normpath(p).split(os.sep)) for p in paths)
    depth = 2
    while len(set(labels)) < len(labels) and depth <= max_depth:
        dupes = {l for l in labels if labels.count(l) > 1}
        labels = [suffix(p, depth) if l in dupes else l
                  for l, p in zip(labels, paths)]
        depth += 1
    if len(set(labels)) < len(labels):
        labels = [f"{i}:{l}" for i, l in enumerate(labels)]
    return labels


def stitch(paths: List[str], out_path: Optional[str] = None,
           labels: Optional[List[str]] = None) -> Dict[str, Any]:
    """Stitch JSONL bundle files into one Perfetto trace (written to
    ``out_path`` when given); returns the per-trace summary.  Labels
    default to the bundles' basenames, grown with leading path
    components until unique (:func:`_bundle_labels`)."""
    if labels is None:
        labels = _bundle_labels(paths)
    bundles = [(label, read_jsonl(p)) for label, p in zip(labels, paths)]
    doc, summary = stitch_records(bundles)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, default=str)
        summary["out"] = out_path
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.obs.export",
        description="Stitch per-process telemetry JSONL bundles into one "
                    "Perfetto trace, grouped by trace_id "
                    "(docs/observability.md 'Fleet telemetry plane').")
    ap.add_argument("bundles", nargs="+", metavar="GLOB",
                    help="JSONL bundle files (bench.py --trace-out, serve "
                         "--trace-out, daemon --trace-out)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the merged Perfetto trace here")
    args = ap.parse_args(argv)
    paths: List[str] = []
    for pat in args.bundles:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else
                     ([pat] if os.path.exists(pat) else []))
    if not paths:
        sys.stderr.write("export: no bundles matched\n")
        return 2
    summary = stitch(paths, out_path=args.out)
    sys.stdout.write(json.dumps(summary, sort_keys=True) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
