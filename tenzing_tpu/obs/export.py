"""Telemetry sinks: JSONL for machines, Chrome trace-event JSON for Perfetto.

Two serializations of one tracer's records:

* **JSONL** — one JSON object per line (``kind`` = "span" | "event"), in
  timestamp order: the archival format downstream tooling greps / re-derives
  statistics from (the "search telemetry is training data" direction of
  arXiv:2203.02530).  Round-trips through :func:`read_jsonl`.
* **Chrome trace-event JSON** — the ``{"traceEvents": [...]}`` format
  Perfetto (https://ui.perfetto.dev) and chrome://tracing load directly:
  spans as complete events (``ph: "X"`` with ``ts``/``dur``), events as
  thread-scoped instants (``ph: "i"``), plus ``ph: "M"`` metadata naming
  each pid "rank N" so merged multi-host bundles read as one process row per
  rank.  Field semantics: https://docs.google.com/document/d/1CvAClvFfyA5R-
  PhYUmn5OOQtYMH4h6I0nSsKchNAySU (ts/dur in microseconds).

Multi-host merging: each rank writes its own bundle; concatenating the JSONL
files (or the ``traceEvents`` lists) merges them — records are pid-tagged
with the rank, timestamps are unix-anchored.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from tenzing_tpu.obs.tracer import Tracer


def _snapshot(tracer: Tracer):
    """One consistent read of the tracer for both sinks.  Non-blocking
    (``block=False``): export runs from atexit AND from the signal trap
    (bench.py ``write_telemetry``), where the interrupted thread may hold
    the tracer lock — a blocking read there deadlocks the Ctrl-C path.
    ``flush_open`` closes copies of all in-flight spans so an interrupted
    run's bundle keeps them (marked ``flushed: true``) and every exported
    ``parent`` id resolves to an exported span."""
    spans, events, open_spans = tracer.snapshot(block=False, flush_open=True)
    return spans + open_spans, events


def _records(tracer: Tracer) -> List[Dict[str, Any]]:
    spans, events = _snapshot(tracer)
    recs = [s.to_json() for s in spans]
    recs += [e.to_json() for e in events]
    recs.sort(key=lambda r: r["ts_us"])
    return recs


def to_jsonl(tracer: Tracer) -> str:
    """All records, one JSON object per line, timestamp-ordered."""
    return "".join(
        json.dumps(r, sort_keys=True, default=str) + "\n"
        for r in _records(tracer)
    )


def write_jsonl(tracer: Tracer, path: str) -> None:
    with open(path, "w") as f:
        f.write(to_jsonl(tracer))


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL bundle back to record dicts (the round-trip contract)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _category(name: str) -> str:
    """Perfetto category = the subsystem prefix of the record name
    ("mcts.iter" -> "mcts"); names without a dot categorize as themselves."""
    return name.split(".", 1)[0]


def _track_name(tid: int, lanes: Dict[int, int]) -> str:
    """Human name of a thread track: spans that carried a ``lane``
    attribute name their track after the lane; plain threads keep a
    ``thread N`` label (tid 0 — the main thread everywhere in this
    codebase — reads as ``main``)."""
    if tid in lanes:
        return f"lane {lanes[tid]}"
    return "main" if tid == 0 else f"thread {tid}"


def chrome_trace(tracer: Tracer,
                 extra_events: List[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The trace as a Chrome trace-event object (see module docstring).

    Tracks are NAMED (``thread_name`` metadata per (pid, tid), on top of
    the per-rank ``process_name`` rows): a span whose attrs carry a
    ``lane`` names its track ``lane N``, so attribution timelines
    (obs/attrib/explain.py ``timeline_trace_events`` — passed in via
    ``extra_events``, which may carry their own ``M`` metadata) and the
    ordinary spans render as one grouped per-rank trace instead of flat
    anonymous thread rows."""
    trace_events: List[Dict[str, Any]] = []
    pids = set()
    tids = set()  # (pid, tid) pairs needing a thread_name row
    lane_of: Dict[int, int] = {}  # tid -> lane id, when a span declares one
    spans, events = _snapshot(tracer)
    for sp in spans:
        pids.add(sp.pid)
        tids.add((sp.pid, sp.tid))
        lane = sp.attrs.get("lane")
        if isinstance(lane, int):
            lane_of[sp.tid] = lane
        trace_events.append({
            "name": sp.name,
            "cat": _category(sp.name),
            "ph": "X",
            "ts": sp.ts_us,
            "dur": sp.dur_us,
            "pid": sp.pid,
            "tid": sp.tid,
            "args": sp.attrs,
        })
    for ev in events:
        pids.add(ev.pid)
        tids.add((ev.pid, ev.tid))
        trace_events.append({
            "name": ev.name,
            "cat": _category(ev.name),
            "ph": "i",
            "ts": ev.ts_us,
            "pid": ev.pid,
            "tid": ev.tid,
            "s": "t",  # thread-scoped instant
            "args": ev.attrs,
        })
    extra_meta: List[Dict[str, Any]] = []
    for e in extra_events or []:
        if e.get("ph") == "M":
            extra_meta.append(e)
            continue
        pids.add(e.get("pid", 0))
        trace_events.append(e)
    trace_events.sort(key=lambda e: e["ts"])
    meta = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"rank {pid}"}}
        for pid in sorted(pids)
    ]
    meta += [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": _track_name(tid, lane_of)}}
        for pid, tid in sorted(tids)
    ]
    meta += extra_meta
    return {"traceEvents": meta + trace_events, "displayTimeUnit": "ms"}


def write_chrome_trace(tracer: Tracer, path: str,
                       extra_events: List[Dict[str, Any]] = None) -> None:
    """Write the Perfetto-loadable trace; ``extra_events`` appends
    pre-built trace-event dicts (e.g. the attribution profiler's per-lane
    Gantt tracks) into the same bundle."""
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer, extra_events=extra_events), f,
                  default=str)
