"""SLO burn-rate alerting over the fleet's telemetry documents:
``python -m tenzing_tpu.obs.alerts check``.

PR 12 made the fleet *visible* — status documents, metric-snapshot
rings, SLO blocks; nothing *acted* on them.  This module is the acting
half of the watchtower (docs/observability.md "Watchtower"): a
**declarative rule set** evaluated over the documents every long-lived
process already publishes (``status-*.json``, ``metrics-*.json``, the
work queue's lease/poison files), a **firing/resolved state machine**
persisted to an atomic ``alerts-<owner>.json`` document, and a CLI
whose exit code CI can gate on.

**Rule catalog** (:data:`DEFAULT_RULES`; thresholds override via a JSON
file or ``--set rule.param=value``):

* ``slo_burn`` — multi-window burn rate on the exact-tier pct99: one
  snapshot's SLO block gives the *fast* window (current burn =
  ``pct99 / target`` — or vs the committed baseline when no target is
  set), the whole snapshot ring gives the *slow* window (median burn
  across it).  Fires only when **both** exceed their thresholds — the
  standard multi-window trick: a single noisy snapshot cannot page,
  and a real regression cannot hide behind one good heartbeat.
* ``shed_rate`` — the ``serve.shed_rate`` gauge (sheds/sec over the
  last heartbeat window) above ``max_per_s``.
* ``queue_age`` — work items older than ``max_s`` (the drain fleet is
  not keeping up), and the serve loop's ``serve.queue_age_s`` gauge
  above ``max_wait_s`` (requests are aging in the bounded queue).
* ``stale_heartbeat`` — a status document whose ``heartbeat_at`` is
  older than ``max_age_s`` while its state is not ``stopped``: the
  process died without saying so (the exact signature a SIGKILLed
  serve loop or daemon leaves).
* ``poison`` — ``poison-*.json`` appearing in a work queue: a request
  that deterministically kills its drainer is quarantined, and someone
  should look at it.
* ``tracer_drops`` — a snapshot whose tracer retention block shows
  dropped spans/events: telemetry is being lost, the one condition the
  telemetry itself must shout about.
* ``tenant_shed`` — per-tenant fairness (the PR 13 counters,
  ``serve.shed.<tenant>`` / ``serve.timeout.<tenant>``, capped tenant
  set and all): sheds or timeouts attributed to one tenant grew across
  the snapshot ring beyond ``max_shed``/``max_timeout`` — one noisy
  neighbour is eating the fleet's admission budget.
* ``queue_backlog_burn`` — arrival rate (reqlog position deltas across
  the snapshot ring) against the drain fleet's measured rate (per-item
  wall-clock economics from daemon status histories): when cold work
  arrives faster than the fleet drains it, the queue grows without
  bound and the alert says how many daemons would balance it — the
  signal the fleet-autoscaling item will consume
  (:func:`backlog_summary`, also rendered by ``report --follow``).

**State machine** (:class:`AlertBook`): alerts key on
``rule:subject``.  A newly-seen alert transitions to ``firing`` (one
transition, timestamped); seeing it again while firing only refreshes
``last_seen_at``/``value`` — **dedup**, no re-transition.  An alert
absent from an evaluation resolves only after ``resolve_hold_secs``
of continuous absence (**no flapping**: a rule oscillating around its
threshold yields one firing window, not a transition per check).
Resolved entries are retained (bounded) so a re-fire is visibly a
re-fire (``count`` increments, the transition list grows).

**Exit codes** (the CI contract, mirrored from the regression gate):
0 = healthy (nothing firing), 1 = at least one alert firing,
2 = unreadable tree / usage error.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

ALERT_DOC_VERSION = 1
TRANSITIONS_CAP = 20        # per-alert transition history kept
ENTRIES_CAP = 200           # resolved entries retained in the doc

DEFAULT_RULES: Dict[str, Dict[str, Any]] = {
    "slo_burn": {"enabled": True, "severity": "page",
                 "fast_burn": 2.0, "slow_burn": 1.5, "min_window": 3},
    "shed_rate": {"enabled": True, "severity": "page", "max_per_s": 1.0},
    "queue_age": {"enabled": True, "severity": "ticket",
                  "max_s": 600.0, "max_wait_s": 30.0},
    "stale_heartbeat": {"enabled": True, "severity": "page",
                        "max_age_s": 60.0},
    "poison": {"enabled": True, "severity": "ticket"},
    "tracer_drops": {"enabled": True, "severity": "ticket",
                     "max_dropped": 0},
    # 0 = any per-tenant shed/timeout growth across the ring fires; a
    # busy fleet raises these to its tolerated per-tenant budget
    "tenant_shed": {"enabled": True, "severity": "ticket",
                    "max_shed": 0, "max_timeout": 0},
    # arrival must exceed drain by this factor (and by a non-trivial
    # absolute rate) with work actually queued before paging — a
    # momentarily idle fleet with an empty queue is not a backlog
    # max_daemons clamps the recommendation (and the supervisor's
    # scale-up bound): null = ~os.cpu_count(), 0 = unclamped
    "queue_backlog_burn": {"enabled": True, "severity": "page",
                           "burn_ratio": 1.2,
                           "min_arrival_per_s": 0.1,
                           "max_daemons": None},
    # fired from the supervisor's status-doc breaker block: a member
    # slot crash-looped past its restart budget and sits quarantined
    "supervisor_crash_loop": {"enabled": True, "severity": "page"},
    # the serve plane latched a store read-only (ENOSPC/EDQUOT/EROFS):
    # exact answers continue, near/cold tiers shed, the daemon pauses
    # claims.  Fires while the latch doc rides the status/snapshot
    # docs; resolves (via the ledger's hold) once a probe write lands
    # and the latch clears
    "store_unwritable": {"enabled": True, "severity": "page"},
    # segment damage economics: checksum-skip / quarantine counters
    # growing across the snapshot ring (0 = any growth fires) — the
    # store is taking damage faster than anyone runs fsck
    "store_damage_rate": {"enabled": True, "severity": "ticket",
                          "max_damage": 0},
}


class AlertTreeError(ValueError):
    """The fleet tree cannot be read (missing directory, unreadable
    rules file) — a *usage* error (exit 2), never a firing alert."""


@dataclass
class Alert:
    """One active condition from one evaluation pass."""

    rule: str
    subject: str            # which owner/queue/item the rule fired on
    severity: str
    value: Any              # the observed number the rule tripped on
    threshold: Any
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.subject}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "subject": self.subject,
                "severity": self.severity, "value": self.value,
                "threshold": self.threshold, "message": self.message}


def load_rules(path: Optional[str] = None,
               sets: Optional[List[str]] = None) -> Dict[str, Dict[str, Any]]:
    """The effective rule set: :data:`DEFAULT_RULES`, deep-merged with
    an optional JSON file (``{"rule": {"param": value}}``), then with
    ``--set rule.param=value`` overrides.  Unknown rules/params are a
    loud :class:`AlertTreeError` — a typo'd threshold must not silently
    evaluate the default."""
    rules = copy.deepcopy(DEFAULT_RULES)
    if path is not None:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise AlertTreeError(f"rules file {path}: {e}") from e
        if not isinstance(doc, dict):
            raise AlertTreeError(f"rules file {path}: not an object")
        for name, params in doc.items():
            if name not in rules:
                raise AlertTreeError(f"unknown rule {name!r} "
                                     f"(catalog: {sorted(rules)})")
            if not isinstance(params, dict):
                raise AlertTreeError(f"rule {name!r}: params not an object")
            for param in params:
                # same contract as --set: a typo'd param name must not
                # silently leave the real threshold at its default
                if param not in rules[name]:
                    raise AlertTreeError(
                        f"rule {name!r} has no param {param!r} "
                        f"(has {sorted(rules[name])})")
            rules[name].update(params)
    for spec in sets or []:
        name_param, _, raw = spec.partition("=")
        name, _, param = name_param.partition(".")
        if name not in rules or not param or not raw:
            raise AlertTreeError(
                f"--set {spec!r}: expected rule.param=value with rule in "
                f"{sorted(rules)}")
        if param not in rules[name]:
            raise AlertTreeError(
                f"--set {spec!r}: rule {name!r} has no param {param!r} "
                f"(has {sorted(rules[name])})")
        try:
            value: Any = json.loads(raw)
        except ValueError:
            value = raw
        rules[name][param] = value
    return rules


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def burn_of(slo: Dict[str, Any]) -> Optional[float]:
    """One snapshot's SLO burn: pct99 over the operator's target (or
    over the committed baseline when no target is set) — >1 means the
    latency objective is being burned, <=1 means healthy."""
    if not isinstance(slo, dict):
        return None
    pct99 = slo.get("pct99_us")
    denom = slo.get("target_us") or slo.get("baseline_pct99_us")
    if pct99 is None or not denom:
        return None
    return float(pct99) / float(denom)


def _status_docs(directory: str) -> List[Dict[str, Any]]:
    out = []
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("status-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            doc["_file"] = name
            out.append(doc)
    return out


def evaluate(store_dirs: List[str], queue_dirs: List[str],
             rules: Optional[Dict[str, Dict[str, Any]]] = None,
             now: Optional[float] = None) -> List[Alert]:
    """One evaluation pass over the fleet tree (module docstring).
    Strictly read-only.  A named directory that does not exist raises
    :class:`AlertTreeError` — pointing the watchtower at a typo'd path
    must exit 2, not report a vacuously healthy fleet."""
    from tenzing_tpu.obs.metrics import snapshot_history

    rules = rules if rules is not None else copy.deepcopy(DEFAULT_RULES)
    now = time.time() if now is None else now
    alerts: List[Alert] = []

    def on(name: str) -> Optional[Dict[str, Any]]:
        r = rules.get(name) or {}
        return r if r.get("enabled", True) else None

    # the latch doc appears on BOTH the owner's status doc and its
    # metric snapshots; one alert per owner, whichever surfaced first
    ro_fired: set = set()

    def ro_alert(owner: str, ro: Dict[str, Any],
                 r: Dict[str, Any]) -> None:
        if owner in ro_fired:
            return
        ro_fired.add(owner)
        alerts.append(Alert(
            "store_unwritable", owner, r["severity"],
            {"errno": ro.get("errno"), "reason": ro.get("reason")},
            None,
            f"store latched read-only ({ro.get('error', '?')}): exact "
            "answers continue, near/cold tiers shed, claims pause — "
            "clears when a probe write lands (free space / fix the "
            "mount)"))

    for d in list(store_dirs) + list(queue_dirs):
        if not os.path.isdir(d):
            raise AlertTreeError(f"fleet tree: {d} is not a directory")

    seen_status: List[Dict[str, Any]] = []
    for d in dict.fromkeys(list(store_dirs) + list(queue_dirs)):
        try:
            seen_status += _status_docs(d)
            history = snapshot_history(d)
        except OSError as e:
            # isdir passed but the scan failed (permissions, an NFS
            # hiccup): still an unreadable tree — usage error, never a
            # crash out of the follow view's render loop
            raise AlertTreeError(f"fleet tree: {d} unreadable "
                                 f"({e})") from e
        for owner, docs in sorted(history.items()):
            latest = docs[-1]
            if latest.get("state") == "stopped":
                continue  # a drained loop's ring is history, not health
            r = on("slo_burn")
            burns = [b for b in (burn_of(doc.get("slo")) for doc in docs)
                     if b is not None]
            fast = burn_of(latest.get("slo"))
            # min_window: with a 1-2 doc ring the slow median IS the
            # latest value, so the multi-window veto would degenerate —
            # a just-restarted loop's warm-up heartbeat must not page
            if r and fast is not None and \
                    len(burns) >= r.get("min_window", 1):
                slow = _median(burns)
                if fast >= r["fast_burn"] and slow >= r["slow_burn"]:
                    slo = latest["slo"]
                    alerts.append(Alert(
                        "slo_burn", owner, r["severity"],
                        {"fast": round(fast, 3), "slow": round(slow, 3)},
                        {"fast_burn": r["fast_burn"],
                         "slow_burn": r["slow_burn"]},
                        f"{slo.get('histogram', '?')} pct99 "
                        f"{slo.get('pct99_us')}us burning the SLO at "
                        f"{fast:.2f}x now / {slow:.2f}x over the ring "
                        f"(window of {len(burns)})"))
            gauges = (latest.get("metrics") or {}).get("gauges", {})
            r = on("shed_rate")
            shed = gauges.get("serve.shed_rate")
            if r and shed is not None and shed > r["max_per_s"]:
                alerts.append(Alert(
                    "shed_rate", owner, r["severity"], shed,
                    r["max_per_s"],
                    f"shedding {shed}/s (> {r['max_per_s']}/s): the "
                    "loop is refusing load"))
            r = on("queue_age")
            wait = gauges.get("serve.queue_age_s")
            if r and wait is not None and wait > r["max_wait_s"]:
                alerts.append(Alert(
                    "queue_age", f"{owner}:pending", r["severity"], wait,
                    r["max_wait_s"],
                    f"oldest pending request waited {wait}s "
                    f"(> {r['max_wait_s']}s) in the bounded queue"))
            r = on("tenant_shed")
            if r:
                # per-tenant fairness over the snapshot ring: growth of
                # the serve.shed.<tenant> / serve.timeout.<tenant>
                # counters PR 13 records (bounded label set — the
                # listen loop caps distinct tenants and aggregates the
                # rest under "other", so this iteration is bounded too)
                def ring_delta(prefix: str) -> Dict[str, int]:
                    def counters(doc):
                        return (doc.get("metrics") or {}).get(
                            "counters") or {}

                    new, old = counters(docs[-1]), counters(docs[0])
                    out: Dict[str, int] = {}
                    for key, val in new.items():
                        if not key.startswith(prefix):
                            continue
                        try:
                            delta = int(val) - int(old.get(key, 0))
                        except (TypeError, ValueError):
                            continue
                        # a negative delta means the counter reset
                        # (restart) inside the ring: the latest value
                        # IS the growth since then
                        out[key[len(prefix):]] = (delta if delta >= 0
                                                  else int(val))
                    return out

                sheds = ring_delta("serve.shed.")
                touts = ring_delta("serve.timeout.")
                for tenant in sorted(set(sheds) | set(touts)):
                    s, t = sheds.get(tenant, 0), touts.get(tenant, 0)
                    if s > r["max_shed"] or t > r["max_timeout"]:
                        alerts.append(Alert(
                            "tenant_shed", f"{owner}:{tenant}",
                            r["severity"],
                            {"shed": s, "timeout": t},
                            {"max_shed": r["max_shed"],
                             "max_timeout": r["max_timeout"]},
                            f"tenant {tenant!r}: {s} shed(s) / {t} "
                            f"timeout(s) across the snapshot ring "
                            f"(window of {len(docs)}) — one tenant is "
                            "eating the admission budget"))
            r = on("tracer_drops")
            tr = latest.get("tracer") or {}
            dropped = (int(tr.get("dropped_spans") or 0)
                       + int(tr.get("dropped_events") or 0))
            if r and dropped > r["max_dropped"]:
                alerts.append(Alert(
                    "tracer_drops", owner, r["severity"], dropped,
                    r["max_dropped"],
                    f"tracer dropped {dropped} record(s) "
                    f"({tr.get('dropped_spans', 0)} spans / "
                    f"{tr.get('dropped_events', 0)} events): telemetry "
                    "is being lost"))
            r = on("store_unwritable")
            ro = latest.get("store_readonly")
            if r and isinstance(ro, dict):
                ro_alert(owner, ro, r)
            r = on("store_damage_rate")
            if r:
                # segment-damage growth across the ring: the same
                # reset-tolerant delta trick as tenant_shed, over the
                # store's checksum-skip / quarantine counters
                def damage_ctr(doc: Dict[str, Any], key: str) -> int:
                    c = (doc.get("metrics") or {}).get("counters") or {}
                    try:
                        return int(c.get(key, 0))
                    except (TypeError, ValueError):
                        return 0

                damage, detail = 0, {}
                for key in ("serve.store.checksum_failed",
                            "serve.store.segment_quarantined",
                            "serve.store.manifest_quarantined"):
                    new = damage_ctr(docs[-1], key)
                    old = damage_ctr(docs[0], key)
                    delta = (new - old) if new >= old else new
                    if delta > 0:
                        detail[key.rsplit(".", 1)[-1]] = delta
                        damage += delta
                if damage > r["max_damage"]:
                    alerts.append(Alert(
                        "store_damage_rate", owner, r["severity"],
                        detail, r["max_damage"],
                        f"{damage} damaged store record(s) across the "
                        f"snapshot ring (window of {len(docs)}): "
                        f"{detail} — run `serve fsck` and check the "
                        "disk before the manifest rots further"))

    r = on("store_unwritable")
    if r:
        # daemons surface the latch on their status doc only (they
        # publish no snapshot ring) — catch those here
        for st in seen_status:
            if st.get("state") == "stopped":
                continue
            ro = st.get("store_readonly")
            if isinstance(ro, dict):
                ro_alert(str(st.get("owner", st.get("_file", "?"))),
                         ro, r)

    r = on("stale_heartbeat")
    if r:
        for st in seen_status:
            if st.get("state") == "stopped":
                continue  # said goodbye properly
            try:
                age = now - float(st.get("heartbeat_at", 0))
            except (TypeError, ValueError):
                continue
            if age > r["max_age_s"]:
                alerts.append(Alert(
                    "stale_heartbeat",
                    str(st.get("owner", st.get("_file", "?"))),
                    r["severity"], round(age, 1), r["max_age_s"],
                    f"{st.get('kind', 'daemon')} heartbeat is "
                    f"{age:.0f}s stale in state "
                    f"{st.get('state', '?')!r}: the process likely died "
                    "without stopping"))

    r = on("supervisor_crash_loop")
    if r:
        for st in seen_status:
            if st.get("kind") != "supervisor":
                continue
            sup = str(st.get("owner", st.get("_file", "?")))
            for member, b in sorted((st.get("breakers") or {}).items()):
                if not isinstance(b, dict) or \
                        b.get("state") not in ("open", "half_open"):
                    continue
                alerts.append(Alert(
                    "supervisor_crash_loop", member, r["severity"],
                    {"state": b.get("state"),
                     "restarts": b.get("restarts_in_window")},
                    {"max_restarts": b.get("max_restarts"),
                     "window_s": b.get("window_s")},
                    f"member {member!r} crash-looped "
                    f"({b.get('restarts_in_window')} restart(s) inside "
                    f"{b.get('window_s')}s): quarantined by {sup} with "
                    f"the breaker {b.get('state')} — the fleet is "
                    "degraded, not flapping"))

    for qd in dict.fromkeys(queue_dirs):
        try:
            names = sorted(os.listdir(qd))
        except OSError as e:
            raise AlertTreeError(f"fleet tree: {qd} unreadable "
                                 f"({e})") from e
        r = on("poison")
        if r:
            for name in names:
                if name.startswith("poison-") and name.endswith(".json"):
                    alerts.append(Alert(
                        "poison", name[len("poison-"):-len(".json")][:16],
                        r["severity"], 1, 0,
                        f"poisoned work item {name}: a request "
                        "deterministically fails its drain"))
        r = on("queue_age")
        if r:
            oldest: Optional[float] = None
            subject = None
            for name in names:
                if not (name.startswith("work-") and
                        name.endswith(".json")):
                    continue
                try:
                    age = now - os.path.getmtime(os.path.join(qd, name))
                except OSError:
                    continue
                if oldest is None or age > oldest:
                    oldest, subject = age, name
            if oldest is not None and oldest > r["max_s"]:
                alerts.append(Alert(
                    "queue_age", qd, r["severity"], round(oldest, 1),
                    r["max_s"],
                    f"work item {subject} has waited {oldest:.0f}s "
                    f"(> {r['max_s']}s): the drain fleet is not "
                    "keeping up"))

    r = on("queue_backlog_burn")
    if r:
        bl = backlog_summary(store_dirs, queue_dirs,
                             max_daemons=r.get("max_daemons"))
        arrival, drain = bl["arrival_per_s"], bl["drain_per_s"]
        burning = (arrival >= r["min_arrival_per_s"] and bl["depth"] > 0
                   and (drain <= 0 or arrival / drain >= r["burn_ratio"]))
        if burning:
            alerts.append(Alert(
                "queue_backlog_burn", "fleet", r["severity"],
                {"arrival_per_s": arrival, "drain_per_s": drain,
                 "depth": bl["depth"]},
                {"burn_ratio": r["burn_ratio"],
                 "min_arrival_per_s": r["min_arrival_per_s"]},
                f"cold work arrives at {arrival:.2f}/s but "
                f"{bl['daemons']} daemon(s) drain {drain:.2f}/s "
                f"(depth {bl['depth']}): the queue grows without bound "
                f"— run ~{bl['recommended_daemons']} daemon(s) to "
                "balance"))
    return alerts


def backlog_summary(store_dirs: List[str],
                    queue_dirs: List[str],
                    max_daemons: Optional[int] = None,
                    quarantined_owners: Optional[set] = None
                    ) -> Dict[str, Any]:
    """Arrival-vs-drain economics for the ``queue_backlog_burn`` rule
    and the follow view's ``burn`` line: arrival/s from reqlog position
    deltas across each live serve loop's snapshot ring (fallback: the
    served+shed+timeout counter deltas), fleet drain/s from each live
    daemon's measured per-item wall clock (status-doc history), queue
    depth from the work files themselves, and the daemon count that
    would balance the two.  ``recommended_daemons`` is clamped to
    ``max_daemons`` (``None`` = ~os.cpu_count(); ``0`` = unclamped —
    the raw figure stays in ``recommended_daemons_raw``) so one burst
    against a slow drain cannot recommend an absurd fleet for the
    host.  Member slots quarantined by a live supervisor's crash-loop
    breakers are excluded from capacity (their stale status docs would
    otherwise inflate it) and reported as ``quarantined_daemons``.
    ``quarantined_owners`` lets an in-process supervisor union its OWN
    in-memory open/half-open breaker owners into that exclusion — its
    breaker state is fresher than the published status snapshots (a
    member can trip between status publishes, and the supervisor's own
    doc write can lag), so the capacity estimate it scales on never
    counts a member it has itself quarantined.
    Read-only and damage-tolerant: unreadable pieces contribute zero,
    never raise."""
    import math

    from tenzing_tpu.obs.metrics import snapshot_history

    arrival = 0.0
    for d in dict.fromkeys(store_dirs):
        if not os.path.isdir(d):
            continue
        try:
            history = snapshot_history(d)
        except OSError:
            continue
        for _owner, docs in sorted(history.items()):
            if docs[-1].get("state") == "stopped" or len(docs) < 2:
                continue

            def seen(doc) -> Optional[float]:
                rl = doc.get("reqlog")
                if isinstance(rl, dict) and rl.get("records") is not None:
                    try:
                        return float(rl["records"])
                    except (TypeError, ValueError):
                        return None
                c = doc.get("counters")
                if isinstance(c, dict):
                    try:
                        return float(
                            sum(v for k, v in c.items()
                                if k.startswith("served_")
                                or k in ("shed", "timeouts")))
                    except TypeError:
                        return None
                return None

            try:
                dt = float(docs[-1]["written_at"]) - \
                    float(docs[0]["written_at"])
            except (KeyError, TypeError, ValueError):
                continue
            n0, n1 = seen(docs[0]), seen(docs[-1])
            if dt > 0 and n0 is not None and n1 is not None and n1 > n0:
                arrival += (n1 - n0) / dt

    drain = 0.0
    daemons = 0
    quarantined = 0
    walls: List[float] = []
    for qd in dict.fromkeys(queue_dirs):
        if not os.path.isdir(qd):
            continue
        try:
            docs = _status_docs(qd)
        except OSError:
            continue
        # a live supervisor's open/half-open breakers name quarantined
        # member slots: a crash-looped member leaves a stale (never
        # "stopped") status doc behind, which must not count as drain
        # capacity — or recommended_daemons under-recommends exactly
        # while the fleet is degraded
        bad_members = set(str(o) for o in (quarantined_owners or ()))
        for st in docs:
            if st.get("kind") != "supervisor" or \
                    st.get("state") == "stopped":
                continue
            for member, b in (st.get("breakers") or {}).items():
                if isinstance(b, dict) and \
                        b.get("state") in ("open", "half_open"):
                    bad_members.add(str(member))
        for st in docs:
            # only drain daemons count toward fleet capacity — the
            # serve loop and the supervisor publish the same status
            # shape but drain nothing
            if st.get("kind") in ("serve_loop", "supervisor") or \
                    st.get("state") == "stopped":
                continue
            if str(st.get("owner", "")) in bad_members:
                quarantined += 1
                continue
            ws = []
            for h in st.get("history") or []:
                try:
                    w = float(h.get("wall_s"))
                except (TypeError, ValueError):
                    continue
                if w > 0 and h.get("outcome") == "completed":
                    ws.append(w)
            daemons += 1
            if ws:
                walls += ws
                drain += 1.0 / (sum(ws) / len(ws))

    depth = 0
    for qd in dict.fromkeys(queue_dirs):
        try:
            depth += sum(1 for n in os.listdir(qd)
                         if n.startswith("work-") and n.endswith(".json"))
        except OSError:
            pass

    per_item_s = (sum(walls) / len(walls)) if walls else None
    if arrival > 0 and per_item_s:
        recommended = max(1, int(math.ceil(arrival * per_item_s)))
    else:
        recommended = max(1, daemons)
    if max_daemons is None:
        max_daemons = os.cpu_count() or 4
    clamped = recommended if max_daemons <= 0 \
        else min(recommended, int(max_daemons))
    return {"arrival_per_s": round(arrival, 3),
            "drain_per_s": round(drain, 3),
            "daemons": daemons, "quarantined_daemons": quarantined,
            "depth": depth,
            "per_item_s": round(per_item_s, 3) if per_item_s else None,
            "recommended_daemons": clamped,
            "recommended_daemons_raw": recommended,
            "max_daemons": max_daemons if max_daemons > 0 else None}


# -- firing/resolved state machine -------------------------------------------

class AlertBook:
    """The persistent alert ledger (module docstring): load the previous
    ``alerts-<owner>.json``, :meth:`apply` one evaluation's active set,
    write the updated document atomically."""

    def __init__(self, path: str, owner: str = "alerts",
                 resolve_hold_secs: float = 0.0,
                 log: Optional[Callable[[str], None]] = None):
        self.path = path
        self.owner = owner
        self.resolve_hold_secs = float(resolve_hold_secs)
        self._log = log

    def load(self) -> Dict[str, Any]:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and \
                    doc.get("version", 0) <= ALERT_DOC_VERSION and \
                    isinstance(doc.get("alerts"), dict):
                return doc
        except (OSError, ValueError):
            pass
        return {"version": ALERT_DOC_VERSION, "owner": self.owner,
                "alerts": {}}

    def apply(self, active: List[Alert],
              now: Optional[float] = None) -> Dict[str, Any]:
        now = time.time() if now is None else now
        doc = self.load()
        entries: Dict[str, Dict[str, Any]] = doc["alerts"]
        active_by_key = {a.key: a for a in active}
        for key, a in sorted(active_by_key.items()):
            e = entries.get(key)
            if e is None or e.get("state") != "firing":
                # (re-)fire: ONE transition, count incremented — a
                # resolved entry re-firing is visibly a re-fire
                prev_count = int(e.get("count", 0)) if e else 0
                transitions = list(e.get("transitions", [])) if e else []
                transitions.append({"to": "firing", "at": now})
                entries[key] = {
                    **a.to_json(),
                    "state": "firing",
                    "count": prev_count + 1,
                    "first_fired_at": (e or {}).get("first_fired_at", now),
                    "fired_at": now,
                    "last_seen_at": now,
                    "resolved_at": None,
                    "transitions": transitions[-TRANSITIONS_CAP:],
                }
                if self._log is not None:
                    self._log(f"alert firing: {key} — {a.message}")
            else:
                # dedup: still firing, refresh the observation only
                e.update(a.to_json())
                e["state"] = "firing"
                e["last_seen_at"] = now
        for key, e in entries.items():
            if key in active_by_key or e.get("state") != "firing":
                continue
            seen = float(e.get("last_seen_at") or e.get("fired_at") or 0)
            if now - seen >= self.resolve_hold_secs:
                # hysteresis: absent long enough — resolve (one
                # transition); inside the hold window it keeps firing,
                # so threshold oscillation cannot flap the ledger
                e["state"] = "resolved"
                e["resolved_at"] = now
                e.setdefault("transitions", []).append(
                    {"to": "resolved", "at": now})
                e["transitions"] = e["transitions"][-TRANSITIONS_CAP:]
                if self._log is not None:
                    self._log(f"alert resolved: {key}")
        # bound the ledger: drop the stalest RESOLVED entries beyond the
        # cap (firing entries are never dropped — they are the point)
        resolved = [(float(e.get("resolved_at") or 0), k)
                    for k, e in entries.items()
                    if e.get("state") == "resolved"]
        if len(entries) > ENTRIES_CAP:
            resolved.sort()
            for _, k in resolved[:len(entries) - ENTRIES_CAP]:
                entries.pop(k, None)
        doc.update({"version": ALERT_DOC_VERSION, "owner": self.owner,
                    "updated_at": now,
                    "firing": sorted(k for k, e in entries.items()
                                     if e.get("state") == "firing")})
        from tenzing_tpu.utils.atomic import atomic_dump_json

        atomic_dump_json(self.path, doc, prefix=".alerts.")
        return doc


def firing_lines(store_dirs: List[str], queue_dirs: List[str],
                 rules: Optional[Dict[str, Dict[str, Any]]] = None
                 ) -> List[str]:
    """Live firing-alert lines for the follow view (obs/report.py
    ``--follow``): one read-only evaluation with the effective rules,
    nothing persisted; a missing directory renders as a line instead of
    raising — the fleet view must keep rendering through damage."""
    try:
        active = evaluate([d for d in store_dirs if os.path.isdir(d)],
                          [d for d in queue_dirs if os.path.isdir(d)],
                          rules=rules)
    except AlertTreeError as e:
        return [f"alert  evaluation failed: {e}"]
    return [f"ALERT  [{a.severity}] {a.rule} {a.subject}: {a.message}"
            for a in active]


# -- CLI ---------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.obs.alerts",
        description="Evaluate the watchtower rule catalog over the "
                    "fleet's status/metric-snapshot documents and "
                    "persist the firing/resolved ledger "
                    "(docs/observability.md 'Watchtower').")
    sub = ap.add_subparsers(dest="cmd", required=True)
    pc = sub.add_parser("check", help="one evaluation pass; exit 0 "
                                      "healthy / 1 firing / 2 unreadable")
    pc.add_argument("--store", nargs="*", default=None, metavar="DIR",
                    help="segmented store directories (status docs + "
                         "metric-snapshot rings)")
    pc.add_argument("--queue-dir", nargs="*", default=None, metavar="DIR",
                    help="work-queue directories (daemon status docs, "
                         "poison quarantine, item ages)")
    pc.add_argument("--rules", default=None, metavar="PATH",
                    help="JSON rule overrides merged over the catalog")
    pc.add_argument("--set", dest="sets", action="append", default=None,
                    metavar="RULE.PARAM=VALUE",
                    help="one threshold override (repeatable)")
    pc.add_argument("--state", default=None, metavar="PATH",
                    help="alert ledger path (default alerts-<owner>.json "
                         "in the first --store/--queue-dir)")
    pc.add_argument("--owner", default="alerts",
                    help="ledger owner tag (one ledger per fleet tree)")
    pc.add_argument("--hold", type=float, default=0.0, metavar="SECS",
                    help="resolve hysteresis: an alert must stay absent "
                         "this long before firing -> resolved")
    args = ap.parse_args(argv)
    stores = args.store or []
    queues = args.queue_dir or []
    if not stores and not queues:
        ap.error("check needs --store and/or --queue-dir")
    state = args.state or os.path.join(
        (stores + queues)[0], f"alerts-{args.owner}.json")
    try:
        rules = load_rules(args.rules, args.sets)
        active = evaluate(stores, queues, rules=rules)
        book = AlertBook(state, owner=args.owner,
                         resolve_hold_secs=args.hold,
                         log=lambda m: sys.stderr.write(m + "\n"))
        # an unwritable ledger is a broken watchtower, not a firing
        # alert: it must exit 2 like any other unreadable-tree error so
        # a CI gate never mistakes the crash for a verdict
        doc = book.apply(active)
    except (AlertTreeError, OSError) as e:
        sys.stderr.write(f"alerts: {e}\n")
        return 2
    firing = [doc["alerts"][k] for k in doc.get("firing", [])]
    sys.stdout.write(json.dumps({
        "firing": [{k: e[k] for k in ("rule", "subject", "severity",
                                      "value", "message")}
                   for e in firing],
        "n_firing": len(firing),
        "n_resolved": sum(1 for e in doc["alerts"].values()
                          if e.get("state") == "resolved"),
        "state": state,
    }, sort_keys=True) + "\n")
    return 1 if firing else 0


if __name__ == "__main__":
    sys.exit(main())
