"""Measured host-noise floors: how much latency is the host's fault.

The ROADMAP's serving item ends on an asserted-by-hand number — the
residual exact-tier pct99 is "wake-cold dominated scheduler noise
(hot-loop floor ~17us p50 / 26us p99), revisit on a quieter host" —
measured once, in a shell loop, and then quoted forever.  This module
makes that floor a *recorded, gateable quantity* (docs/observability.md
"Causal analysis"): two micro-probes sampled N times with the same
statistical noise rejection the benchmarker uses (bench/randomness.py
runs test), summarized into a ``host_noise`` block that
``serve/replay.py`` stamps into every SERVE_BENCH document.

* **timer-wake** — overshoot of a short ``time.sleep`` (requested vs
  observed, in us): what a blocking wait actually costs on this host —
  the floor under any latency that includes a scheduler wake (condition
  variables, bounded-queue handoff, paced submission).
* **hot-spin** — overshoot of a busy-wait to a near deadline: the floor
  with the scheduler out of the picture — clock granularity plus
  preemption noise, the best this host can time anything.

Downstream consumers (obs/report.py):

* the report CLI renders floor-vs-measured-tail ("pct99 is 3.8x the
  wake floor — host-bound") so a tail that sits on the floor is not
  mistaken for a serving bug;
* the SERVE_BENCH regression gate downgrades a cross-host comparison to
  ``inconclusive`` when the two documents' floors differ materially
  (:func:`floors_differ`) — a slower host is not a regression.

Stdlib-only; probes are injectable (``clock``/``sleeper``) so tests run
deterministically against a scripted clock.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.bench.randomness import runs_test_z
from tenzing_tpu.utils.numeric import percentile

NOISE_VERSION = 1
DEFAULT_SAMPLES = 64
# requested sleep for the timer-wake probe: long enough that the request
# itself is not sub-granularity, short enough that 64 samples cost ~6ms
TIMER_SLEEP_US = 100.0
# busy-wait deadline for the hot-spin probe (the ROADMAP's hot-loop
# floor measured ~17us p50 on the reference host at this horizon)
SPIN_TARGET_US = 20.0
# is_random's 95%-confidence default (bench/randomness.py)
RUNS_Z_CRIT = 1.96
# floors more than this factor apart (either direction) make two
# SERVE_BENCH documents incomparable hosts (floors_differ)
FLOOR_DIFF_FACTOR = 2.0
# a measured tail within this factor of the wake floor is host-bound:
# the host's scheduler, not the serving path, owns the residual
HOST_BOUND_FACTOR = 5.0


def probe_timer_wake(samples: int = DEFAULT_SAMPLES,
                     sleep_us: float = TIMER_SLEEP_US,
                     clock: Optional[Callable[[], float]] = None,
                     sleeper: Optional[Callable[[float], None]] = None,
                     ) -> List[float]:
    """Overshoot (us) of ``samples`` short sleeps: observed minus
    requested, floored at 0 — the scheduler-wake latency floor."""
    clock = clock if clock is not None else time.perf_counter
    sleeper = sleeper if sleeper is not None else time.sleep
    req_s = sleep_us / 1e6
    out: List[float] = []
    for _ in range(max(1, int(samples))):
        t0 = clock()
        sleeper(req_s)
        out.append(max(0.0, (clock() - t0) * 1e6 - sleep_us))
    return out


def probe_hot_spin(samples: int = DEFAULT_SAMPLES,
                   target_us: float = SPIN_TARGET_US,
                   clock: Optional[Callable[[], float]] = None,
                   ) -> List[float]:
    """Overshoot (us) of ``samples`` busy-waits to a ``target_us``
    deadline — the no-scheduler floor (clock granularity + preemption)."""
    clock = clock if clock is not None else time.perf_counter
    out: List[float] = []
    for _ in range(max(1, int(samples))):
        t0 = clock()
        deadline = t0 + target_us / 1e6
        now = t0
        while now < deadline:
            now = clock()
        out.append(max(0.0, (now - t0) * 1e6 - target_us))
    return out


def series_summary(xs: List[float]) -> Dict[str, Any]:
    """p50/p99/mean/max over one probe series plus its runs-test verdict
    (``iid`` False flags drift/interference during the probe itself)."""
    s = sorted(xs)
    z = runs_test_z(xs)
    return {
        "count": len(s),
        "p50_us": round(percentile(s, 50), 2),
        "p99_us": round(percentile(s, 99), 2),
        "mean_us": round(sum(s) / len(s), 2),
        "max_us": round(s[-1], 2),
        "runs_z": round(z, 3),
        "iid": bool(abs(z) <= RUNS_Z_CRIT),
    }


def probe_host_noise(samples: int = DEFAULT_SAMPLES, retries: int = 1,
                     sleep_us: float = TIMER_SLEEP_US,
                     spin_target_us: float = SPIN_TARGET_US,
                     clock: Optional[Callable[[], float]] = None,
                     sleeper: Optional[Callable[[float], None]] = None,
                     ) -> Dict[str, Any]:
    """The ``host_noise`` block (module docstring): both probes, sampled
    ``samples`` times.  A series failing the runs test is re-probed (up
    to ``retries`` extra passes — the same reject-and-retry discipline
    bench/randomness.py gives measurements); the last pass is recorded
    either way, its ``iid`` flag telling the reader whether even the
    floor measurement was quiet."""
    attempts = 0
    wake = spin = None
    wake_s: Dict[str, Any] = {}
    spin_s: Dict[str, Any] = {}
    for attempt in range(max(0, int(retries)) + 1):
        attempts = attempt + 1
        wake = probe_timer_wake(samples, sleep_us, clock=clock,
                                sleeper=sleeper)
        spin = probe_hot_spin(samples, spin_target_us, clock=clock)
        wake_s, spin_s = series_summary(wake), series_summary(spin)
        if wake_s["iid"] and spin_s["iid"]:
            break
    return {
        "version": NOISE_VERSION,
        "samples": int(samples),
        "sleep_us": sleep_us,
        "spin_target_us": spin_target_us,
        "attempts": attempts,
        "timer_wake_us": wake_s,
        "hot_spin_us": spin_s,
        "host": socket.gethostname(),
        "measured_at": time.time(),
    }


def floors_differ(a: Optional[Dict[str, Any]], b: Optional[Dict[str, Any]],
                  factor: float = FLOOR_DIFF_FACTOR) -> Optional[str]:
    """Why two ``host_noise`` blocks are incomparable, or None when they
    are close enough (or either is missing — absence never *claims* a
    host difference).  Floors below 1us are clamped before the ratio so
    clock-granularity jitter cannot manufacture a 'different host'."""
    if not isinstance(a, dict) or not isinstance(b, dict):
        return None
    for key, label in (("timer_wake_us", "timer-wake"),
                       ("hot_spin_us", "hot-spin")):
        try:
            fa = max(1.0, float((a.get(key) or {}).get("p99_us")))
            fb = max(1.0, float((b.get(key) or {}).get("p99_us")))
        except (TypeError, ValueError):
            continue
        ratio = fa / fb if fa >= fb else fb / fa
        if ratio > factor:
            return (f"{label} p99 floor {fa:.1f}us vs {fb:.1f}us "
                    f"({ratio:.1f}x apart, > {factor:.1f}x)")
    return None


def floor_vs_tail(block: Optional[Dict[str, Any]], pct99_us: Optional[float],
                  host_bound_factor: float = HOST_BOUND_FACTOR,
                  ) -> Optional[Dict[str, Any]]:
    """The floor-vs-measured-tail verdict the report CLI renders: how
    many wake floors tall the measured pct99 is, and whether that makes
    the tail host-bound (the host's scheduler owns it) or serving-bound
    (the code does)."""
    if not isinstance(block, dict) or pct99_us is None:
        return None
    try:
        floor = float((block.get("timer_wake_us") or {}).get("p99_us"))
    except (TypeError, ValueError):
        return None
    ratio = float(pct99_us) / max(floor, 1e-9)
    host_bound = ratio <= host_bound_factor
    return {
        "wake_floor_p99_us": floor,
        "pct99_us": float(pct99_us),
        "ratio": round(ratio, 2),
        "host_bound": host_bound,
        "line": (f"pct99 {pct99_us:.1f}us is {ratio:.1f}x the measured "
                 f"wake floor ({floor:.1f}us) — "
                 f"{'host-bound' if host_bound else 'serving-bound'}"),
    }
