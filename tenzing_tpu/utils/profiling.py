"""Device-side schedule profiling: jax.profiler traces + overlap analysis.

SURVEY.md §5 maps the reference's device-side story (host-side phase counters,
``counters.hpp``) to JAX profiler traces on TPU.  This module is that
component: capture an ``xplane`` trace of a schedule running under the
executor, and parse it programmatically to measure how much wall time has a
transfer (DMA/copy) event concurrent with device compute — the quantity a
searched overlap schedule exists to create.  ``experiments/profile_overlap.py``
is the driver that archives the halo naive-vs-overlap evidence
(experiments/PROFILE_OVERLAP.json).

The analysis is keyword-based over the device planes' event names: transfer
events (copy/dma/transfer/send/recv/infeed/outfeed) vs compute events
(fusion/slice/convert/...), with outer control events (while/loop) excluded —
they span the whole program and would make every DMA look concurrent.
Intervals are coalesced before intersection so each nanosecond counts once.
"""

from __future__ import annotations

import glob
from pathlib import Path
from typing import Dict, List, Sequence as Seq, Tuple

TRANSFER_KEYWORDS = ("copy", "dma", "transfer", "infeed", "outfeed", "send",
                     "recv", "all-reduce", "reduce-scatter", "all-gather",
                     "all-to-all", "collective", "permute", "rdma")
COMPUTE_KEYWORDS = ("fusion", "dynamic", "slice", "pad", "convert", "reshape",
                    "add", "concatenate", "custom-call", "custom_call", "dot",
                    "matmul", "gelu", "broadcast", "select", "iota",
                    "transpose", "mosaic")
# outer control events span the whole program and would make every DMA look
# concurrent — they are neither transfer nor compute nor "unclassified"
CONTROL_KEYWORDS = ("while", "loop", "condition", "body", "call", "region")


def capture_trace(executor, order, out_dir, iters: int = 3) -> Tuple[Path, float]:
    """Run ``order`` ``iters`` times under ``jax.profiler.trace`` and return
    (trace directory, wall seconds).  The schedule is compiled and warmed
    first so the trace holds steady-state execution, not compilation."""
    import time

    import jax

    run_n = executor.prepare_n(order)
    run_n(1)  # compile + warm outside the trace
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(str(out_dir)):
        run_n(iters)
    return out_dir, time.perf_counter() - t0


def merge_intervals(ivs: Seq[Tuple[int, int]]) -> List[List[int]]:
    """Coalesce intervals so busy time and intersections count each
    nanosecond once."""
    out: List[List[int]] = []
    for a, b in sorted(ivs):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def analyze_trace(trace_dir) -> Dict[str, float]:
    """Transfer-vs-compute concurrency on the device planes of the newest
    xplane file under ``trace_dir`` (see module docstring for the method)."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(str(Path(trace_dir) / "**" / "*.xplane.pb"),
                             recursive=True))
    if not paths:
        return {"error": f"no xplane under {trace_dir}"}
    data = ProfileData.from_file(paths[-1])
    xfers: List[Tuple[int, int]] = []
    computes: List[Tuple[int, int]] = []
    unclassified: List[Tuple[int, int]] = []
    for plane in data.planes:
        pname = plane.name.lower()
        if not ("tpu" in pname or "device" in pname or "xla" in pname):
            continue
        for line in plane.lines:
            for ev in line.events:
                nm = (ev.name or "").lower()
                iv = (ev.start_ns, ev.end_ns)
                if iv[1] <= iv[0]:
                    continue
                if any(k in nm for k in TRANSFER_KEYWORDS):
                    xfers.append(iv)
                elif any(k in nm for k in COMPUTE_KEYWORDS):
                    computes.append(iv)
                elif not any(k in nm for k in CONTROL_KEYWORDS):
                    # neither transfer, compute, nor outer control: report it
                    # so silent misclassification is visible (ADVICE r3)
                    unclassified.append(iv)

    def total(ivs):
        return sum(b - a for a, b in merge_intervals(ivs))

    overlap_ns = 0
    computes_merged = merge_intervals(computes)
    for a, b in merge_intervals(xfers):
        for c, d in computes_merged:
            if c >= b:
                break
            lo, hi = max(a, c), min(b, d)
            if hi > lo:
                overlap_ns += hi - lo
    return {
        "xplane": paths[-1],
        "n_transfer_events": len(xfers),
        "n_compute_events": len(computes),
        "n_unclassified_events": len(unclassified),
        "transfer_busy_ms": total(xfers) / 1e6,
        "compute_busy_ms": total(computes) / 1e6,
        "unclassified_busy_ms": total(unclassified) / 1e6,
        "transfer_concurrent_with_compute_ms": overlap_ns / 1e6,
    }
