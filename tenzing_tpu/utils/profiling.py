"""Deprecation shim: device-side profiling moved to the attribution
subsystem (ISSUE 6) — :mod:`tenzing_tpu.obs.attrib.xplane`.

Kept so existing imports (``from tenzing_tpu.utils.profiling import
analyze_trace``) and the archived experiment drivers
(``experiments/profile_overlap.py``, ``experiments/profile_winner.py``)
keep working, the same back-compat discipline as ``utils/counters.py``
(a shim over ``obs/metrics``).  New code should import from
``tenzing_tpu.obs.attrib`` — the xplane capture there is the multi-chip
fallback next to the per-op stepped timing mode the attribution profiler
prefers (docs/observability.md, "Attribution").
"""

from __future__ import annotations

from tenzing_tpu.obs.attrib.xplane import (  # noqa: F401
    COMPUTE_KEYWORDS,
    CONTROL_KEYWORDS,
    TRANSFER_KEYWORDS,
    analyze_trace,
    capture_trace,
    merge_intervals,
)

__all__ = [
    "COMPUTE_KEYWORDS",
    "CONTROL_KEYWORDS",
    "TRANSFER_KEYWORDS",
    "analyze_trace",
    "capture_trace",
    "merge_intervals",
]
