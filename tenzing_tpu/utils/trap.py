"""Signal trapping for partial-result dumps.

Parity target: reference ``src/trap.cpp:9-35``: solvers install a SIGINT/SIGABRT
handler so a wall-clock-limited job (e.g. SLURM ``--signal=SIGABRT@10``) still
dumps the schedules explored so far before dying."""

from __future__ import annotations

import signal
import sys
from typing import Callable, List, Optional

_callbacks: List[Callable[[], None]] = []
_prev_handlers: dict = {}


def _handler(signum, frame):  # pragma: no cover - signal path
    for cb in list(_callbacks):
        try:
            cb()
        except Exception as e:
            # bare write, not the ProgressReporter: a signal handler must
            # not touch shared telemetry state mid-crash
            sys.stderr.write(f"trap: dump callback failed: {e}\n")
    signal.signal(signum, signal.SIG_DFL)
    signal.raise_signal(signum)


def register_handler(dump: Callable[[], None]) -> None:
    """Install ``dump`` to run on SIGINT/SIGABRT (reference register_handler)."""
    _callbacks.append(dump)
    if not _prev_handlers:
        for sig in (signal.SIGINT, signal.SIGABRT):
            _prev_handlers[sig] = signal.signal(sig, _handler)


def unregister_handler(dump: Callable[[], None]) -> None:
    """Remove a callback; the last removal restores the previous handlers so
    Ctrl-C behaves normally again outside a search."""
    if dump in _callbacks:
        _callbacks.remove(dump)
    if not _callbacks and _prev_handlers:
        for sig, prev in _prev_handlers.items():
            signal.signal(sig, prev)
        _prev_handlers.clear()
