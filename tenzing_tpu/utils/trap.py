"""Signal trapping for partial-result dumps.

Parity target: reference ``src/trap.cpp:9-35``: solvers install a SIGINT/SIGABRT
handler so a wall-clock-limited job (e.g. SLURM ``--signal=SIGABRT@10``) still
dumps the schedules explored so far before dying.

Callbacks registered here run *inside a signal handler*: they must not block
on locks the interrupted thread may hold (the obs exporters and
``MetricsRegistry.to_json`` offer ``block=False`` reads for exactly this —
docs/robustness.md), and one callback raising must not silence the others
(:func:`run_callbacks` isolates each; covered by tests/test_trap.py).
"""

from __future__ import annotations

import signal
import sys
from typing import Callable, List

_callbacks: List[Callable[[], None]] = []
_prev_handlers: dict = {}


def run_callbacks() -> int:
    """Run every registered dump callback, isolating failures: a raising
    callback is reported on stderr and the rest still run.  Returns the
    number of callbacks that failed.  Split out of the handler so the
    callback semantics are testable without delivering a real signal."""
    failed = 0
    for cb in list(_callbacks):
        try:
            cb()
        except Exception as e:
            failed += 1
            # bare write, not the ProgressReporter: a signal handler must
            # not touch shared telemetry state mid-crash
            sys.stderr.write(f"trap: dump callback failed: {e}\n")
    return failed


def _handler(signum, frame):  # pragma: no cover - signal path
    run_callbacks()
    signal.signal(signum, signal.SIG_DFL)
    signal.raise_signal(signum)


def installed() -> bool:
    """True while the trap handler owns SIGINT/SIGABRT."""
    return bool(_prev_handlers)


def callbacks() -> List[Callable[[], None]]:
    """Snapshot of the registered callbacks (registration order)."""
    return list(_callbacks)


def register_handler(dump: Callable[[], None]) -> None:
    """Install ``dump`` to run on SIGINT/SIGABRT (reference register_handler)."""
    _callbacks.append(dump)
    if not _prev_handlers:
        for sig in (signal.SIGINT, signal.SIGABRT):
            _prev_handlers[sig] = signal.signal(sig, _handler)


def unregister_handler(dump: Callable[[], None]) -> None:
    """Remove a callback; the last removal restores the previous handlers so
    Ctrl-C behaves normally again outside a search."""
    if dump in _callbacks:
        _callbacks.remove(dump)
    if not _callbacks and _prev_handlers:
        for sig, prev in _prev_handlers.items():
            signal.signal(sig, prev)
        _prev_handlers.clear()
