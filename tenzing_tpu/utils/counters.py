"""Phase-timing counters (reference counters.hpp:26-34 and the MCTS counters,
tenzing-mcts/include/tenzing/mcts/counters.hpp:16-27): accumulate wall time per
solver phase — SELECT / EXPAND / ROLLOUT / REDUNDANT_SYNC / BCAST / BENCHMARK /
BACKPROP — and report at the end of a search (mcts.hpp:311-320)."""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict


class Counters:
    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = ["phase counters:"]
        for name in sorted(self.seconds, key=lambda n: -self.seconds[n]):
            lines.append(
                f"  {name:>16}: {self.seconds[name]:9.3f}s  x{self.counts[name]}"
            )
        return "\n".join(lines)
