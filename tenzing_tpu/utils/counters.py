"""Phase-timing counters (reference counters.hpp:26-34 and the MCTS counters,
tenzing-mcts/include/tenzing/mcts/counters.hpp:16-27): accumulate wall time per
solver phase — SELECT / EXPAND / ROLLOUT / REDUNDANT_SYNC / BCAST / BENCHMARK /
BACKPROP — and report at the end of a search (mcts.hpp:311-320).

Compatibility shim over :mod:`tenzing_tpu.obs.metrics` (ISSUE 1): each
``Counters`` owns a private histogram per phase, and every ``phase()`` block

* observes its duration into that histogram (``seconds``/``counts``/
  ``report()`` keep the exact legacy API and format),
* mirrors it into the process-global registry as
  ``<prefix>.<NAME>.seconds`` — so ``bench.py --metrics-json`` archives the
  solver phase timings without the solvers threading a registry around, and
* opens a ``<prefix>.<NAME>`` span on the global tracer — so enabling
  tracing shows every solver phase nested inside its iteration span in
  Perfetto, at no cost when tracing is disabled.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict

from tenzing_tpu.obs.metrics import MetricsRegistry, get_metrics
from tenzing_tpu.obs.tracer import get_tracer


class Counters:
    def __init__(self, prefix: str = "solver.phase",
                 mirror_global: bool = True) -> None:
        self._registry = MetricsRegistry()
        self._prefix = prefix
        self._mirror_global = mirror_global

    @contextmanager
    def phase(self, name: str, span: bool = True):
        """Time a block under phase ``name``.  ``span=False`` skips the
        tracer span (counters/metrics only) — for per-node inner loops (DFS
        enumeration) where a span per entry would flood the trace."""
        ctx = get_tracer().span(f"{self._prefix}.{name}") if span else None
        if ctx is not None:
            ctx.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if ctx is not None:
                ctx.__exit__(None, None, None)
            self._registry.histogram(name).observe(dt)
            if self._mirror_global:
                get_metrics().histogram(
                    f"{self._prefix}.{name}.seconds").observe(dt)

    @property
    def seconds(self) -> Dict[str, float]:
        """Accumulated wall seconds per phase (legacy dict API)."""
        return {name: h.total
                for name, h in self._registry.histograms().items()}

    @property
    def counts(self) -> Dict[str, int]:
        """Times each phase was entered (legacy dict API)."""
        return {name: h.count
                for name, h in self._registry.histograms().items()}

    def report(self) -> str:
        lines = ["phase counters:"]
        seconds, counts = self.seconds, self.counts
        for name in sorted(seconds, key=lambda n: -seconds[n]):
            lines.append(
                f"  {name:>16}: {seconds[name]:9.3f}s  x{counts[name]}"
            )
        return "\n".join(lines)
