"""Reproducibility stamp: one JSON line with version, VCS state, and argv.

Parity target: reference ``src/reproduce.cpp:22-46`` (``reproduce::dump_with_cli``
prints ``{"version": ..., "git": ..., "argv": [...]}``; version/git-hash are baked
in by CMake from ``git describe``, CMakeLists.txt:21-44).  Here the stamp is
computed at call time: package version from ``tenzing_tpu.__version__``, git
hash/dirty state read from the working tree when available.
"""

from __future__ import annotations

import json
import subprocess
import sys
from typing import List, Optional


def git_info(cwd: Optional[str] = None) -> dict:
    """{"hash": ..., "dirty": bool} of the checkout enclosing this package (not
    the caller's cwd), or {} when not in one (reference bakes this in at
    configure time; we read it live)."""
    import os

    if cwd is None:
        cwd = os.path.dirname(os.path.abspath(__file__))
    try:
        h = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd, capture_output=True, text=True, timeout=5,
        )
        if h.returncode != 0:
            return {}
        s = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=cwd, capture_output=True, text=True, timeout=5,
        )
        return {"hash": h.stdout.strip(), "dirty": bool(s.stdout.strip())}
    except Exception:
        return {}


def stamp(argv: Optional[List[str]] = None) -> dict:
    import jax

    from tenzing_tpu import __version__

    return {
        "tenzing_tpu": __version__,
        "jax": jax.__version__,
        "git": git_info(),
        "argv": list(sys.argv if argv is None else argv),
    }


def dump_with_cli(argv: Optional[List[str]] = None, stream=None) -> str:
    """Print the stamp as one JSON line (reference reproduce.cpp:22-37) and
    return it."""
    line = json.dumps(stamp(argv))
    (stream or sys.stderr).write(line + "\n")
    return line
