"""THE atomic file-write helper (tmp + fsync + rename).

One definition shared by every persistent-state writer in the tree — the
checkpoint state snapshots (fault/checkpoint.py), the quarantine file
(fault/quarantine.py), and the schedule-serving store/work-queue
(serve/store.py).  Readers see either the previous complete file or the
new complete file, never a torn write, and the rename only lands after
the bytes are durably on disk.  Factored out of fault/checkpoint.py
(where it was born) when the serving store would otherwise have grown a
third copy.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict


def fsync_dir(path: str) -> None:
    """fsync a *directory* so a just-landed rename/link inside it is
    durable (POSIX: the rename itself lives in the directory's metadata;
    crash-consistency of the segmented store's publish steps depends on
    it — serve/segments.py).  Best-effort: platforms that refuse to open
    a directory (or to fsync one) degrade to the pre-existing behavior
    rather than failing the write that already landed."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish_sealed(directory: str, make_name, text: str) -> str:
    """Atomically publish one complete, immutable file into
    ``directory``: private temp, fsync, hard-link to the name
    ``make_name()`` returns (called again on a collision with a rival
    publisher — the maker must stamp fresh names), temp unlinked,
    directory fsynced.  A reader can never observe a torn acknowledged
    file.  THE one copy of the sealed-publish dance shared by the
    segmented store's segments and the watchtower's request log
    (serve/segments.py, serve/reqlog.py) — a durability fix here fixes
    both formats.  Returns the published name."""
    os.makedirs(directory, exist_ok=True)
    while True:
        name = make_name()
        final = os.path.join(directory, name)
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, final)
        except FileExistsError:
            continue
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        break
    fsync_dir(directory)
    return name


def atomic_dump_json(path: str, doc: Dict[str, Any],
                     prefix: str = ".atomic.") -> None:
    """Atomically write ``doc`` as sorted-key JSON to ``path``.

    The temp file is created in the destination directory (rename must not
    cross filesystems), fsynced before the rename, and unlinked on any
    failure so aborted writes leave no droppings; the directory is fsynced
    after the rename so the publish itself is durable."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
