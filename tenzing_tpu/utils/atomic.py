"""THE atomic file-write helper (tmp + fsync + rename).

One definition shared by every persistent-state writer in the tree — the
checkpoint state snapshots (fault/checkpoint.py), the quarantine file
(fault/quarantine.py), and the schedule-serving store/work-queue
(serve/store.py).  Readers see either the previous complete file or the
new complete file, never a torn write, and the rename only lands after
the bytes are durably on disk.  Factored out of fault/checkpoint.py
(where it was born) when the serving store would otherwise have grown a
third copy.

Because every durable writer funnels through here, this module is also
THE injectable I/O seam for hostile-filesystem chaos
(fault/fsinject.py): an installed backend gets a checkpoint before each
write/fsync/link/replace, may serve a stale read once, and may skew the
mtimes the lease protocol observes (serve/lease.py).  With no backend
installed — the production default — every hook is a no-op branch on a
None global.  ``$TENZING_FSINJECT`` lazily installs a backend on first
use, so subprocess fleet members inherit a chaos run's faults without
argv plumbing.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional

FSINJECT_ENV = "TENZING_FSINJECT"

_io_backend: Optional[Any] = None
_env_checked = False


def set_io_backend(backend: Optional[Any]) -> None:
    """Install (or with None, remove) the fault-injecting I/O backend —
    fault/fsinject.py is the only production caller."""
    global _io_backend, _env_checked
    _io_backend = backend
    _env_checked = True


def io_backend() -> Optional[Any]:
    """The active backend, lazily installed from ``$TENZING_FSINJECT``
    exactly once.  A malformed env spec raises loudly on the first write
    — a chaos run that silently injects nothing proves nothing."""
    global _env_checked
    if not _env_checked:
        _env_checked = True
        if os.environ.get(FSINJECT_ENV):
            from tenzing_tpu.fault.fsinject import install_from_env

            install_from_env()
    return _io_backend


def _check(op: str, path: str) -> None:
    b = io_backend()
    if b is not None:
        b.check(op, path)


def io_getmtime(path: str) -> float:
    """``os.path.getmtime`` as *observed* through the seam: an installed
    backend may skew or coarsen it — the lease protocol's expiry checks
    read clocks through here so chaos can model NFS/FAT timestamp
    behavior (serve/lease.py)."""
    t = os.path.getmtime(path)
    b = io_backend()
    return b.observe_mtime(path, t) if b is not None else t


def read_json(path: str):
    """``json.load(open(path))`` through the seam: an installed backend
    may serve the file's *previous* complete content once (NFS
    attribute-cache staleness).  Raises OSError/ValueError exactly like
    the plain read."""
    b = io_backend()
    if b is not None:
        doc = b.maybe_stale_json(path)
        if doc is not None:
            return doc
    with open(path) as f:
        return json.load(f)


def fsync_dir(path: str) -> None:
    """fsync a *directory* so a just-landed rename/link inside it is
    durable (POSIX: the rename itself lives in the directory's metadata;
    crash-consistency of the segmented store's publish steps depends on
    it — serve/segments.py).  Best-effort: platforms that refuse to open
    a directory (or to fsync one) degrade to the pre-existing behavior
    rather than failing the write that already landed."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish_sealed(directory: str, make_name, text: str) -> str:
    """Atomically publish one complete, immutable file into
    ``directory``: private temp, fsync, hard-link to the name
    ``make_name()`` returns (called again on a collision with a rival
    publisher — the maker must stamp fresh names), temp unlinked,
    directory fsynced.  A reader can never observe a torn acknowledged
    file.  THE one copy of the sealed-publish dance shared by the
    segmented store's segments and the watchtower's request log
    (serve/segments.py, serve/reqlog.py) — a durability fix here fixes
    both formats.  Returns the published name."""
    os.makedirs(directory, exist_ok=True)
    while True:
        name = make_name()
        final = os.path.join(directory, name)
        tmp = final + ".tmp"
        _check("write", final)
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            _check("fsync", final)
            os.fsync(f.fileno())
        try:
            # the torn-rename kill point: temp bytes durable, link not
            # yet landed — the crash the sealed formats must survive
            _check("link", final)
            os.link(tmp, final)
        except FileExistsError:
            continue
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        break
    fsync_dir(directory)
    return name


def atomic_dump_json(path: str, doc: Dict[str, Any],
                     prefix: str = ".atomic.") -> None:
    """Atomically write ``doc`` as sorted-key JSON to ``path``.

    The temp file is created in the destination directory (rename must not
    cross filesystems), fsynced before the rename, and unlinked on any
    failure so aborted writes leave no droppings; the directory is fsynced
    after the rename so the publish itself is durable."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    _check("write", path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            _check("fsync", path)
            os.fsync(f.fileno())
        # the torn-rename kill point (see publish_sealed)
        _check("replace", path)
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
