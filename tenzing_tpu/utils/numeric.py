"""Numeric helpers (reference include/tenzing/numeric.hpp / src/numeric.cpp):
avg/med/var/stddev, Pearson correlation (used by MCTS strategies,
numeric.hpp:57-109), prime factorization for rank-grid layout, round_up."""

from __future__ import annotations

import math
from typing import List, Sequence


def avg(xs: Sequence[float]) -> float:
    return sum(xs) / len(xs)


def med(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n % 2:
        return s[n // 2]
    return 0.5 * (s[n // 2 - 1] + s[n // 2])


def var(xs: Sequence[float]) -> float:
    m = avg(xs)
    return sum((x - m) ** 2 for x in xs) / len(xs)


def stddev(xs: Sequence[float]) -> float:
    return math.sqrt(var(xs))


def corr(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Pearson correlation coefficient (reference numeric.hpp:57-109); 0 when
    either side is constant."""
    if len(xs) != len(ys) or not xs:
        raise ValueError("corr needs two equal-length non-empty series")
    mx, my = avg(xs), avg(ys)
    sx, sy = stddev(xs), stddev(ys)
    if sx == 0.0 or sy == 0.0:
        return 0.0
    n = len(xs)
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / n
    return cov / (sx * sy)


def prime_factors(n: int) -> List[int]:
    """Ascending prime factorization (reference numeric.cpp:11-33; used for
    device-grid layout, halo_run_strategy.hpp:80-98)."""
    out: List[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= x (reference numeric.cpp:35-42)."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    return ((x + multiple - 1) // multiple) * multiple


def percentile(sorted_xs: Sequence[float], pct: float) -> float:
    """Nearest-rank percentile over a pre-sorted series (reference
    benchmarker.cpp:157-166 indexing convention)."""
    if not sorted_xs:
        raise ValueError("empty series")
    i = min(len(sorted_xs) - 1, max(0, int(round(pct / 100.0 * (len(sorted_xs) - 1)))))
    return sorted_xs[i]
