"""Numeric helpers (reference include/tenzing/numeric.hpp / src/numeric.cpp):
avg/med/var/stddev, Pearson correlation (used by MCTS strategies,
numeric.hpp:57-109), prime factorization for rank-grid layout, round_up."""

from __future__ import annotations

import math
from typing import List, Sequence


def avg(xs: Sequence[float]) -> float:
    return sum(xs) / len(xs)


def med(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n % 2:
        return s[n // 2]
    return 0.5 * (s[n // 2 - 1] + s[n // 2])


def var(xs: Sequence[float]) -> float:
    m = avg(xs)
    return sum((x - m) ** 2 for x in xs) / len(xs)


def stddev(xs: Sequence[float]) -> float:
    return math.sqrt(var(xs))


def corr(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Pearson correlation coefficient (reference numeric.hpp:57-109); 0 when
    either side is constant."""
    if len(xs) != len(ys) or not xs:
        raise ValueError("corr needs two equal-length non-empty series")
    mx, my = avg(xs), avg(ys)
    sx, sy = stddev(xs), stddev(ys)
    if sx == 0.0 or sy == 0.0:
        return 0.0
    n = len(xs)
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / n
    return cov / (sx * sy)


def gelu_tanh(x):
    """tanh-approximate gelu on a numpy array — matches ``jax.nn.gelu``'s
    default exactly, so host-side model references agree with the device path
    (shared by the MoE / pipeline / TP-MLP buffer builders)."""
    import numpy as np

    return 0.5 * x * (1.0 + np.tanh(np.sqrt(2.0 / np.pi) * (x + 0.044715 * x**3)))


def gelu_tanh_grad(x):
    """Analytic derivative of :func:`gelu_tanh` on a numpy array — the host
    float64 reference for device-side ``jax.vjp`` of ``jax.nn.gelu`` (used by
    training-step expected-gradient builders)."""
    import numpy as np

    c = np.sqrt(2.0 / np.pi)
    u = c * (x + 0.044715 * x**3)
    th = np.tanh(u)
    return 0.5 * (1.0 + th) + 0.5 * x * (1.0 - th**2) * c * (1.0 + 3 * 0.044715 * x**2)


def prime_factors(n: int) -> List[int]:
    """Ascending prime factorization (reference numeric.cpp:11-33; used for
    device-grid layout, halo_run_strategy.hpp:80-98)."""
    out: List[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= x (reference numeric.cpp:35-42)."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    return ((x + multiple - 1) // multiple) * multiple


def percentile(sorted_xs: Sequence[float], pct: float) -> float:
    """Nearest-rank percentile over a pre-sorted series (reference
    benchmarker.cpp:157-166 indexing convention)."""
    if not sorted_xs:
        raise ValueError("empty series")
    i = min(len(sorted_xs) - 1, max(0, int(round(pct / 100.0 * (len(sorted_xs) - 1)))))
    return sorted_xs[i]


def paired_speedup(
    base: Sequence[float], cand: Sequence[float], seed: int = 0, n_boot: int = 2000
) -> tuple:
    """(median speedup, ci_lo, ci_hi): per-iteration paired speedup base/cand
    with a seeded bootstrap 95% CI over the iteration-aligned ratio series.

    Input series must be iteration-aligned (``EmpiricalBenchmarker.
    benchmark_batch_times``: iteration k visits every schedule once, in a
    shuffled order) so each ratio compares measurements taken back-to-back
    under the same system conditions — slow drift common to both schedules
    cancels instead of inflating the verdict's variance.  Extends the
    reference's decorrelation idea (benchmarker.cpp:21-76) from "shuffle the
    visit order" to "compare within the iteration"."""
    import random as _random

    if len(base) != len(cand) or not base:
        raise ValueError("paired_speedup needs two equal-length non-empty series")
    ratios = [b / c for b, c in zip(base, cand)]
    rng = _random.Random(seed)
    n = len(ratios)
    meds = sorted(med([ratios[rng.randrange(n)] for _ in range(n)]) for _ in range(n_boot))
    return med(ratios), percentile(meds, 2.5), percentile(meds, 97.5)
