"""One-shot library initialization and research-notice gate.

Parity target: reference ``src/init.cpp:24-67`` — ``tenzing::init()`` is
idempotent, prints a research-software notice once, and requires acknowledgment
via an environment variable before long runs proceed silently.

TPU-native differences: no MPI_Init to wrap (process bring-up is
``jax.distributed.initialize``, owned by parallel/control_plane.py), so init()
is pure host-side bookkeeping: the notice gate plus recording argv/start time
for the reproduce stamp (utils/reproduce.py).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional

ACK_ENV = "TENZING_TPU_ACK_NOTICE"

NOTICE = """\
tenzing_tpu is research software: schedules it explores are executed and timed
on the attached devices.  Set {env}=1 to acknowledge and silence this notice.
""".format(env=ACK_ENV)

_initialized = False
_init_time: Optional[float] = None


def is_initialized() -> bool:
    return _initialized


def init_time() -> Optional[float]:
    """Wall-clock time of the first init() call (for reproduce stamps)."""
    return _init_time


def init(stream=None) -> None:
    """Idempotent library init (reference init.cpp:24-41): print the research
    notice unless acknowledged via the environment."""
    global _initialized, _init_time
    if _initialized:
        return
    _initialized = True
    _init_time = time.time()
    if os.environ.get(ACK_ENV, "") not in ("1", "true", "yes"):
        (stream or sys.stderr).write(NOTICE)


def _reset_for_tests() -> None:
    global _initialized, _init_time
    _initialized = False
    _init_time = None
