"""Neighborhood search over schedules: hill-climbing in decision space.

Beyond the reference's two solvers (exhaustive DFS, MCTS): the measured
anytime driver showed hand-built greedy incumbents repeatedly winning the
paired final while MCTS rollouts — exploring the full space from scratch —
lagged.  This solver searches the *neighborhood of an incumbent* instead: a
schedule is represented by the decision list that builds it from
``State(graph)``; a neighbor substitutes ONE decision (a different lane
binding, implementation choice, or execution order pick) and completes the
rest by following the original plan where it still applies, falling back to
the phase policy where it does not.  First-improvement hill climbing under a
benchmark budget then refines the incumbent with measured steps — the classic
local-search complement to MCTS's global exploration, sharing the same SDP
machinery, benchmarkers, and caching as the other solvers.
"""

from __future__ import annotations

import random as _random
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence as Seq, Tuple

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    candidate_failed,
    schedule_id,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import (
    AssignLane,
    ChooseOp,
    Decision,
    ExecuteOp,
    ExpandOp,
    State,
)
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer


def phase_policy(platform, phases: Seq[str],
                 prefer: Optional[Callable[[str, List[str]], Optional[str]]] = None,
                 priority: Optional[Callable[[str], int]] = None):
    """A policy closure for :func:`drive`: expand compounds eagerly, resolve
    ChoiceOps via ``prefer(choice_op_name, choice_names) -> chosen name`` (or
    the first choice), round-robin lane bindings, and execute in ``phases``
    order with the sync-gating discipline of solve/greedy.py.

    ``priority`` (op name -> int) overrides the prefix-index phase of an op —
    finer-than-phase disciplines (e.g. the halo paired await/unpack interleave,
    models/halo_pipeline.paired_priority) express per-op orderings while
    reusing the same gating machinery."""
    from tenzing_tpu.core.sync_ops import SyncOp

    lane_rr = [0]

    def phase(op) -> int:
        name = op.name()
        if priority is not None:
            return priority(name)
        for i, p in enumerate(phases):
            if name.startswith(p):
                return i
        return 0

    def policy(st: State, ds: List[Decision]) -> Decision:
        expands = [d for d in ds if isinstance(d, ExpandOp)]
        if expands:
            return expands[0]
        chooses = [d for d in ds if isinstance(d, ChooseOp)]
        if chooses:
            grp = sorted(
                (d for d in chooses if d.op.name() == chooses[0].op.name()),
                key=lambda d: d.choice.name(),
            )
            if prefer is not None:
                want = prefer(grp[0].op.name(), [d.choice.name() for d in grp])
                pick = next((d for d in grp if d.choice.name() == want), None)
                if pick is not None:
                    return pick
            return grp[0]
        assigns = sorted(
            (d for d in ds if isinstance(d, AssignLane)), key=lambda d: d.op.name()
        )
        if assigns:
            opname = assigns[0].op.name()
            lane = platform.lanes[lane_rr[0] % len(platform.lanes)]
            lane_rr[0] += 1
            return next(
                (d for d in assigns if d.op.name() == opname and d.lane == lane),
                assigns[0],
            )
        execs = [d for d in ds if isinstance(d, ExecuteOp)]
        real = sorted(
            (d for d in execs if not isinstance(d.op, SyncOp)),
            key=lambda d: (phase(d.op), d.op.name()),
        )
        syncs = sorted(
            (d for d in execs if isinstance(d.op, SyncOp)), key=lambda d: d.op.desc()
        )
        done = {op.name() for op in st.sequence}
        pending_min = min(
            (phase(v) for v in st.graph.vertices() if v.name() not in done),
            default=99,
        )
        if real and (not syncs or phase(real[0].op) <= pending_min):
            return real[0]
        return syncs[0]

    return policy


def drive(graph: Graph, platform, policy) -> Tuple[Sequence, List[Decision]]:
    """Run ``policy`` to a terminal state, recording the decision list."""
    st = State(graph)
    decisions: List[Decision] = []
    while not st.is_terminal():
        ds = st.get_decisions(platform)
        d = policy(st, ds)
        decisions.append(d)
        st = st.apply(d)
    return st.sequence, decisions


def replay_with_substitution(
    graph: Graph, platform, decisions: List[Decision], i: int,
    alt: Decision, fallback,
) -> Tuple[Sequence, List[Decision]]:
    """The neighbor: apply ``decisions[:i]``, then ``alt`` instead of
    ``decisions[i]``, then complete by taking any still-offered decision from
    the original plan (earliest-planned first) and falling back to
    ``fallback`` when the plan no longer applies (e.g. after an
    implementation-choice flip invalidated downstream ops)."""
    st = State(graph)
    taken: List[Decision] = []
    for d in decisions[:i]:
        st = st.apply(d)
        taken.append(d)
    st = st.apply(alt)
    taken.append(alt)
    plan = list(decisions[i + 1:])
    while not st.is_terminal():
        ds = st.get_decisions(platform)
        offered = {d.key(): d for d in ds}
        pick = None
        for j, p in enumerate(plan):
            got = offered.get(p.key())
            if got is not None:
                pick = got
                del plan[j]
                break
        if pick is None:
            pick = fallback(st, ds)
        st = st.apply(pick)
        taken.append(pick)
    return st.sequence, taken


@dataclass
class LocalOpts:
    """``budget`` counts benchmarked DISTINCT schedules: canonical-key
    dedup skips no-op neighbors (a substitution that rebuilds the identical
    schedule) without charging the budget, and a neighbor already measured by
    an earlier solver through a shared ``CachingBenchmarker`` (cache hit —
    instant, no device time) is likewise free (ADVICE r3).

    ``prescreen`` (a ``learn.surrogate.SurrogateBenchmarker``) prunes
    neighbors before they are measured: a candidate whose optimistic
    prediction (``mu - prescreen_z * (sigma_cand + sigma_incumbent)``) is
    still worse than the incumbent's prediction is skipped without charging
    the budget — the learned model spends the measurement budget on
    neighbors it cannot rule out.

    ``paired=True`` makes each accept decision DRIFT-IMMUNE: the neighbor and
    the current incumbent are measured back-to-back as one decorrelated
    2-schedule batch and the move is taken only when the paired ratio's
    bootstrap CI clears 1.0.  Without it, first-improvement climbing under a
    drifting chip accepts moves because the *chip* sped up between the
    incumbent's old measurement and the neighbor's new one (observed in the
    r4 driver: a climb chain "improving" 142 -> 96 ms that ranked below its
    own seed in the paired screen).  Needs a benchmarker exposing
    ``benchmark_batch_times`` (EmpiricalBenchmarker, directly or as the
    ``.inner`` of a CachingBenchmarker)."""

    budget: int = 24
    bench_opts: BenchOpts = field(default_factory=BenchOpts)
    seed: int = 0
    max_alts_per_step: int = 3
    paired: bool = False
    prescreen: Optional[object] = None  # learn SurrogateBenchmarker
    prescreen_z: float = 2.0
    # fault.checkpoint.SearchCheckpoint: snapshots the climb cursor (budget
    # spent, accepted moves) per measured neighbor; resume re-executes the
    # seeded climb against the journal-restored cache (cache hits are free
    # — the budget is re-spent only on schedules never measured before), so
    # the accepted chain reconstructs deterministically
    checkpoint: Optional[object] = None
    # independent soundness gate (verify.ScheduleVerifier): the incumbent
    # and every neighbor are verified before they are measured; an unsound
    # neighbor is rejected like one that failed to compile
    verify: Optional[object] = None
    # compile prefetcher (bench.pipeline.PrefetchingBenchmarker): each
    # position's neighbor batch is built up front and hinted before the
    # sequential measure loop, so neighbor k+1's compile overlaps neighbor
    # k's measurement.  Building the batch early is pure replay (no RNG):
    # None (the default) is bit-identical to prefetch-off.
    prefetch: Optional[object] = None
    # cross-worker search exchange (search.fleet.SharedSearchState): a fleet
    # of climbs over different seeds shares (a) a winner-takes-all claim
    # registry of canonical schedule keys — ``claim(seq) -> False`` means
    # another worker already paid for this neighbor, skip it budget-free
    # like a local dedup hit — and (b) incumbent snapshots published on
    # every accepted move (``note_incumbent(cost_s, seq)``), the fleet's
    # "allreduce incumbents" half.  None = solo climb, bit-identical to the
    # pre-fleet behavior.
    shared: Optional[object] = None


@dataclass
class LocalResult:
    sims: List = field(default_factory=list)  # SimResult-compatible entries
    final: object = None  # the accepted chain tip (the climb's official output)

    def best(self):
        return min(self.sims, key=lambda s: s.result.pct50) if self.sims else None


def hill_climb(
    graph: Graph, platform, benchmarker, phases: Seq[str],
    prefer=None, opts: Optional[LocalOpts] = None, priority=None,
) -> LocalResult:
    """First-improvement hill climbing from the phase-policy incumbent."""
    from tenzing_tpu.solve.mcts.mcts import SimResult

    from tenzing_tpu.core.sequence import canonical_key

    opts = opts if opts is not None else LocalOpts()
    rng = _random.Random(opts.seed)
    # a FRESH policy per drive/replay: phase_policy carries a round-robin
    # lane counter, and sharing one closure would make the schedule a given
    # (position, alternative) neighbor maps to depend on how many fallback
    # assignments happened earlier in the run
    fresh = lambda: phase_policy(platform, phases, prefer, priority)
    result = LocalResult()

    def unsound(seq_, where):
        """True (and reported) when the soundness gate rejects ``seq_`` —
        the climb treats it exactly like a neighbor that failed to
        compile, without spending any device time."""
        if opts.verify is None:
            return False
        verdict = opts.verify(seq_)
        if verdict.ok:
            return False
        import sys

        from tenzing_tpu.verify.soundness import report_unsound

        report_unsound(where, seq_, verdict)
        sys.stderr.write(
            "hill-climb: schedule rejected by the soundness verifier "
            f"({verdict.witness()})\n")
        return True

    def measured(seq_):
        """Benchmark + record; returns (result | None, charge) where
        ``charge`` is False for a cache hit (instant, no device time) — the
        single free-cache-hit policy both the incumbent and the neighbor loop
        use.  ``None`` result = the schedule failed to compile/run (rejected,
        same policy as paired_step)."""
        if unsound(seq_, "local.measure"):
            return None, False
        pre_hits = getattr(benchmarker, "hits", None)
        try:
            res = benchmarker.benchmark(seq_, opts.bench_opts)
        except Exception as e:
            import sys

            from tenzing_tpu.fault.errors import DeviceLostError

            if isinstance(e, DeviceLostError):
                raise  # fatal escalation, never a neighbor verdict
            candidate_failed("local.measure", seq_, e)
            sys.stderr.write(
                "hill-climb: schedule rejected (failed to compile/run: "
                f"{type(e).__name__}: {str(e)[:200]})\n"
            )
            return None, True
        result.sims.append(SimResult(order=seq_, result=res))
        return res, pre_hits is None or benchmarker.hits == pre_hits

    batch_owner = benchmarker
    batcher = getattr(benchmarker, "benchmark_batch_times", None)
    if batcher is None:
        batch_owner = getattr(benchmarker, "inner", None)
        batcher = getattr(batch_owner, "benchmark_batch_times", None)
    use_paired = opts.paired and batcher is not None

    def paired_step(cur_seq, cand_seq):
        """(candidate BenchResult | None, accept, charge) from one
        decorrelated 2-schedule batch: accept only when the paired cur/cand
        ratio's CI clears 1.0; ``charge`` is False when the batch was
        answered from a journal replay (JournalingBenchmarker.batch_hits —
        the same free-cache-hit budget policy as ``measured``, so a resumed
        climb re-spends budget only on batches never run before).  A
        neighbor that fails to COMPILE (e.g. an ordering whose liveness
        needs more HBM than the chip has — observed on the halo flagship:
        several multi-GB grid versions kept alive at once) is a reject, not
        a crash: infeasible-on-hardware is a legitimate verdict for a
        schedule."""
        from tenzing_tpu.bench.benchmarker import BenchResult
        from tenzing_tpu.utils.numeric import paired_speedup

        pair_seed = rng.randrange(1 << 30)
        if unsound(cand_seq, "local.paired"):
            return None, False, False
        pre_hits = getattr(batch_owner, "batch_hits", None)
        try:
            times = batcher([cur_seq, cand_seq], opts.bench_opts, seed=pair_seed)
        except Exception as e:  # compile/runtime failure of the candidate
            import sys

            from tenzing_tpu.fault.errors import DeviceLostError

            if isinstance(e, DeviceLostError):
                raise  # fatal escalation, never a neighbor verdict
            candidate_failed("local.paired", cand_seq, e)
            sys.stderr.write(
                "hill-climb: neighbor rejected (failed to compile/run: "
                f"{type(e).__name__}: {str(e)[:200]})\n"
            )
            return None, False, True
        charge = pre_hits is None or batch_owner.batch_hits == pre_hits
        m, lo, _ = paired_speedup(times[0], times[1], seed=pair_seed + 1)
        res = BenchResult.from_times(times[1])
        result.sims.append(SimResult(order=cand_seq, result=res))
        return res, (m > 1.0 and lo > 1.0), charge

    seq, decisions = drive(graph, platform, fresh())
    cur, charge = measured(seq)
    if cur is None:
        raise RuntimeError(
            "hill-climb incumbent schedule failed to compile/run — nothing "
            "to climb from"
        )
    seen = {canonical_key(seq)}
    spent = 1 if charge else 0
    accepted = 0
    if opts.shared is not None:
        opts.shared.note_incumbent(cur.pct50, seq)

    def save_cursor():
        if opts.checkpoint is not None:
            opts.checkpoint.save_state(
                climb={"spent": spent, "accepted": accepted,
                       "n_sims": len(result.sims)})

    save_cursor()

    def sweep_order(decs):
        """Shuffled positions, structural decisions (implementation choices,
        lane bindings) first — they are sparse in the list but carry the
        biggest schedule differences."""
        struct = [i for i, d in enumerate(decs)
                  if isinstance(d, (ChooseOp, AssignLane))]
        struct_set = set(struct)
        rest = [i for i in range(len(decs)) if i not in struct_set]
        rng.shuffle(struct)
        rng.shuffle(rest)
        return struct + rest

    improved = True
    while spent < opts.budget and improved:
        improved = False
        for i in sweep_order(decisions):
            # re-derive the state at position i to enumerate alternatives
            st = State(graph)
            for d in decisions[:i]:
                st = st.apply(d)
            ds = st.get_decisions(platform)
            alts = [d for d in ds if d.key() != decisions[i].key()]
            rng.shuffle(alts)
            if opts.prefetch is not None:
                # the whole neighbor batch is materialized before the
                # measure loop: replay_with_substitution is deterministic
                # and RNG-free, so building candidate k+1 early changes
                # nothing — but it lets the prefetcher compile it while
                # candidate k measures
                neighbors = [
                    (alt, *replay_with_substitution(
                        graph, platform, decisions, i, alt, fresh()))
                    for alt in alts[: opts.max_alts_per_step]
                ]
                opts.prefetch.prefetch(
                    [cs for _, cs, _ in neighbors
                     if canonical_key(cs) not in seen])
            else:
                # prefetch off: replay lazily, exactly the pre-pipeline
                # cost model (a first-improvement break pays for no
                # neighbor it never visits)
                neighbors = (
                    (alt, *replay_with_substitution(
                        graph, platform, decisions, i, alt, fresh()))
                    for alt in alts[: opts.max_alts_per_step]
                )
            for alt, cand_seq, cand_dec in neighbors:
                key = canonical_key(cand_seq)
                if key in seen:
                    # a no-op neighbor (e.g. swapping which of two Expands
                    # goes first yields the identical schedule) — skip
                    # WITHOUT charging the budget
                    continue
                seen.add(key)
                if opts.shared is not None and not opts.shared.claim(cand_seq):
                    # another fleet worker already claimed this exact
                    # canonical schedule — the subtrees stay *dynamically*
                    # disjoint, and the skip is budget-free like a local
                    # dedup hit
                    continue
                if opts.prescreen is not None:
                    mu_c, s_c = opts.prescreen.predict(cand_seq)
                    mu_i, s_i = opts.prescreen.predict(seq)
                    if mu_c - opts.prescreen_z * (s_c + s_i) > mu_i:
                        # even the optimistic bound is worse than the
                        # incumbent's prediction: prune without measuring
                        get_metrics().counter(
                            "learn.prune.local_skipped").inc()
                        tr = get_tracer()
                        if tr.enabled:
                            tr.event("learn.prune", where="local",
                                     schedule=schedule_id(cand_seq))
                        continue
                if use_paired:
                    res, accept, charge = paired_step(seq, cand_seq)
                    if charge:
                        spent += 1
                else:
                    res, charge = measured(cand_seq)
                    if charge:
                        spent += 1  # cache hits are free: don't charge
                    accept = res is not None and res.pct50 < cur.pct50
                if accept:  # first improvement: move
                    cur, seq, decisions = res, cand_seq, cand_dec
                    improved = True
                    accepted += 1
                    if opts.shared is not None:
                        opts.shared.note_incumbent(cur.pct50, seq)
                    save_cursor()  # accepted moves only: the cursor is
                    # consistency metadata (resume replays the journal), so
                    # a per-neighbor atomic rewrite would just double the
                    # measurement loop's sync I/O
                    break
                if spent >= opts.budget:
                    break
            if improved or spent >= opts.budget:
                break
    save_cursor()  # final spend/accept tallies
    result.final = SimResult(order=seq, result=cur)
    return result
