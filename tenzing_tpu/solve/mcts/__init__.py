from tenzing_tpu.solve.mcts.mcts import MctsOpts, MctsResult, explore  # noqa: F401
from tenzing_tpu.solve.mcts.node import Node  # noqa: F401
