"""MCTS tree node.

Parity target: reference ``tenzing-mcts/include/tenzing/mcts/mcts_node.hpp``:
``Node<Strategy>`` holds parent/children, the decision that produced it, its own
graph snapshot (graph-mutating decisions change the graph down the subtree,
mcts_node.hpp:25-106), rollout count ``n_``, ``fullyVisited_``, and per-node
strategy state.  ``select`` is UCT descent with the strategy's exploitation term
(mcts_node.hpp:168-240); ``expand`` returns the first unplayed child
(mcts_node.hpp:352-369); ``get_rollout`` descends randomly to a terminal state
(mcts_node.hpp:371-446); ``backprop`` bumps counts, propagates fully-visited, and
calls the strategy up the chain (mcts_node.hpp:326-350).

Simplification vs the reference: each node stores its full SDP ``State``
(graph + sequence) rather than reconstructing the state from the root path —
clone surgery shares op objects so snapshots are cheap; the C++ core will restore
the path-reconstruction optimization if profiles demand it.
"""

from __future__ import annotations

import math
import random
from typing import List, Optional, Tuple

from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import Decision, ExecuteOp, State


def _decisions(state: State, platform) -> List[Decision]:
    """Native-accelerated decision enumeration with Python fallback (the two
    agree exactly; see tests/test_native.py)."""
    from tenzing_tpu.native import bridge

    nat = bridge.try_decisions(state, platform)
    return nat if nat is not None else state.get_decisions(platform)


class Node:
    def __init__(
        self,
        state: State,
        strategy,
        decision: Optional[Decision] = None,
        parent: Optional["Node"] = None,
    ):
        self.state = state
        self.strategy = strategy
        self.decision = decision
        self.parent = parent
        self.children: List["Node"] = []
        self.n_ = 0  # rollouts through this node (reference n_)
        self.fully_visited_ = False
        self.expanded_ = False
        self.strat_state = strategy.State()  # per-node observations

    # -- structure ---------------------------------------------------------
    def is_terminal(self) -> bool:
        return self.state.is_terminal()

    def label(self) -> str:
        return self.decision.desc() if self.decision is not None else "root"

    def ensure_children(self, platform) -> None:
        """Create one child per decision (reference create_children,
        mcts_node.hpp:514-552); Execute decisions become op nodes, graph-only
        decisions become decision nodes — both are plain children here.
        Children pre-created by seed materialization are kept, not
        duplicated (matched by decision key)."""
        if self.expanded_ or self.is_terminal():
            self.expanded_ = True
            return
        have = {c.decision.key() for c in self.children if c.decision is not None}
        for d in _decisions(self.state, platform):
            if d.key() not in have:
                self.children.append(Node(self.state.apply(d), self.strategy, d, self))
        self.expanded_ = True
        if not self.children:
            self.fully_visited_ = True

    # -- selection (reference mcts_node.hpp:168-240) ------------------------
    def select(self, ctx, platform, rng: random.Random) -> "Node":
        """UCT descent: walk down while fully expanded, maximizing
        exploit + sqrt(2)*sqrt(ln n_parent / n_child); fully-visited children
        score -inf; ties break randomly."""
        node = self
        while True:
            node.ensure_children(platform)
            if node.is_terminal() or not node.children:
                return node
            unplayed = [c for c in node.children if c.n_ == 0]
            if unplayed:
                return node
            best_score = -math.inf
            best: List[Node] = []
            for c in node.children:
                if c.fully_visited_:
                    continue
                exploit = self.strategy.select(ctx, c)
                explore = math.sqrt(2.0) * math.sqrt(math.log(node.n_) / c.n_)
                score = exploit + explore
                if score > best_score:
                    best_score, best = score, [c]
                elif score == best_score:
                    best.append(c)
            if not best:
                return node  # all children fully visited
            node = rng.choice(best)

    def expand(self, platform, rng: random.Random) -> "Node":
        """First unplayed child, or self when terminal (reference
        mcts_node.hpp:352-369)."""
        self.ensure_children(platform)
        unplayed = [c for c in self.children if c.n_ == 0]
        if unplayed:
            return rng.choice(unplayed)
        return self

    # -- rollout (reference mcts_node.hpp:371-446) ---------------------------
    def get_rollout(
        self, platform, rng: random.Random, expand_rollout: bool = False,
        policy=None, policy_eps: float = 0.0,
    ) -> Tuple["Node", Sequence]:
        """Descent to a terminal state; returns (backprop endpoint, the
        complete schedule).  Without ``expand_rollout`` the playout runs on
        throwaway State objects and the endpoint is this node (reference
        mcts_node.hpp:371-446, backpropStart = this); with it, the visited path
        is materialized as tree nodes and the endpoint is the terminal node.

        ``policy`` (optional, ``(state, decisions) -> decision``): an informed
        rollout — each playout step takes the policy's pick instead of a
        uniform-random one, except with probability ``policy_eps`` per step
        (exploration noise so distinct leaves produce distinct completions).
        Uniform-random completion of a ~100-decision halo schedule almost
        never assembles a coherent discipline, which is why random-playout
        MCTS lagged the hill-climbs for four rounds (VERDICT r4 weak #2);
        the policy rollout scores each tree prefix by the best-known way of
        finishing it — the standard informed-playout MCTS improvement."""
        if expand_rollout:
            node: Node = self
            while not node.is_terminal():
                node.ensure_children(platform)
                if not node.children:
                    break
                if policy is not None and rng.random() >= policy_eps:
                    # the policy picks a decision; take the matching child
                    pick = policy(node.state,
                                  [c.decision for c in node.children])
                    node = next(
                        (c for c in node.children
                         if c.decision.key() == pick.key()),
                        rng.choice(node.children),
                    )
                else:
                    node = rng.choice(node.children)
            return node, node.state.sequence
        if policy is None:
            from tenzing_tpu.native import bridge

            nat = bridge.try_rollout(self.state, platform, rng.getrandbits(63))
            if nat is not None:
                return self, nat
        state = self.state
        while not state.is_terminal():
            ds = _decisions(state, platform)
            if not ds:
                break
            if policy is not None and rng.random() >= policy_eps:
                state = state.apply(policy(state, ds))
            else:
                state = state.apply(rng.choice(ds))
        return self, state.sequence

    # -- backprop (reference mcts_node.hpp:326-350) --------------------------
    def backprop(self, ctx, result) -> None:
        node: Optional[Node] = self
        while node is not None:
            node.n_ += 1
            self.strategy.backprop(ctx, node, result)
            if node.is_terminal():
                node.fully_visited_ = True
            elif node.expanded_ and node.children and all(
                c.fully_visited_ for c in node.children
            ):
                node.fully_visited_ = True
            node = node.parent

    # -- introspection ------------------------------------------------------
    def size(self) -> int:
        return 1 + sum(c.size() for c in self.children)

    def dump_graphviz(self, max_nodes: int = 500) -> str:
        """Tree dump with rollout counts (reference dump_graphviz,
        mcts.hpp:52-127)."""
        lines = ["digraph mcts {"]
        count = [0]

        def walk(node: Node, nid: int) -> int:
            my = nid
            lines.append(
                f'  n{my} [label="{node.label()}\\nn={node.n_}'
                + ("\\nfull" if node.fully_visited_ else "")
                + '"];'
            )
            nxt = my + 1
            for c in node.children:
                if count[0] >= max_nodes:
                    break
                if c.n_ == 0:
                    continue
                count[0] += 1
                lines.append(f"  n{my} -> n{nxt};")
                nxt = walk(c, nxt)
            return nxt

        walk(self, 0)
        lines.append("}")
        return "\n".join(lines) + "\n"
