"""MCTS selection strategies, pluggable into the solver.

Parity target: the reference strategy menu (one header each under
``tenzing-mcts/include/tenzing/mcts/``): Random, Unvisited, FastMin, AvgTime,
Coverage, AntiCorrelation, NormAntiCorr, NormRootCorr, BalanceHistogram.
Contract (mcts_strategy.hpp:13-27): a strategy provides ``Context`` (search-wide
state; the driver sets ``ctx.root``), per-node ``State`` (observations), a
``select(ctx, node) -> float`` exploitation term, and
``backprop(ctx, node, result)``.

Observations are the benchmarked pct10 time of each rollout through the node
(the statistic the reference strategies record, mcts_strategy_fast_min.hpp:63-64).
"""

from __future__ import annotations

import math
import random
from typing import List, Optional

from tenzing_tpu.utils.numeric import avg, corr


class _Times:
    __slots__ = ("times",)

    def __init__(self) -> None:
        self.times: List[float] = []


def _histogram(times: List[float], lo: float, hi: float, bins: int = 10) -> List[float]:
    h = [0.0] * bins
    if hi <= lo:
        hi = lo + 1e-12
    for t in times:
        i = min(bins - 1, max(0, int((t - lo) / (hi - lo) * bins)))
        h[i] += 1.0
    return h


class StrategyBase:
    """Shared plumbing: times recorded on every node along the backprop path."""

    class Context:
        def __init__(self, seed: int = 0):
            self.root = None  # set by the driver
            self.rng = random.Random(seed)

    State = _Times

    @staticmethod
    def backprop(ctx, node, result) -> None:
        node.strat_state.times.append(result.pct10)

    @staticmethod
    def select(ctx, node) -> float:
        return 0.0

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _root_range(ctx):
        rt = ctx.root.strat_state.times
        if not rt:
            return 0.0, 1.0
        return min(rt), max(rt)


class Random(StrategyBase):
    """Uniformly random child preference (reference mcts_strategy_random.hpp:17-55)."""

    @staticmethod
    def select(ctx, node) -> float:
        return ctx.rng.random()


class Unvisited(StrategyBase):
    """Infinite preference for never-timed children
    (reference mcts_strategy_unvisited.hpp:14-38)."""

    @staticmethod
    def select(ctx, node) -> float:
        return math.inf if not node.strat_state.times else 0.0


class FastMin(StrategyBase):
    """1 - normalized distance of the child's best time from the root's best
    (reference mcts_strategy_fast_min.hpp:17-66)."""

    @staticmethod
    def select(ctx, node) -> float:
        ts = node.strat_state.times
        if not ts:
            return 0.0
        lo, hi = StrategyBase._root_range(ctx)
        if hi <= lo:
            return 1.0
        return 1.0 - (min(ts) - lo) / (hi - lo)


class AvgTime(StrategyBase):
    """Mean of the child's times normalized to the root's range
    (reference mcts_strategy_avg_time.hpp:18-60)."""

    @staticmethod
    def select(ctx, node) -> float:
        ts = node.strat_state.times
        if not ts:
            return 0.0
        lo, hi = StrategyBase._root_range(ctx)
        if hi <= lo:
            return 1.0
        return 1.0 - (avg(ts) - lo) / (hi - lo)


class Coverage(StrategyBase):
    """The child's time-range coverage of its parent's range
    (reference mcts_strategy_coverage.hpp:16-102)."""

    @staticmethod
    def select(ctx, node) -> float:
        ts = node.strat_state.times
        parent = node.parent
        if not ts or parent is None or not parent.strat_state.times:
            return 0.0
        plo, phi = min(parent.strat_state.times), max(parent.strat_state.times)
        if phi <= plo:
            return 0.0
        return (max(ts) - min(ts)) / (phi - plo)


class AntiCorrelation(StrategyBase):
    """Prefer children whose 10-bin time histogram anti-correlates with the
    parent's (reference mcts_strategy_anti_corr.hpp:15-90)."""

    @staticmethod
    def select(ctx, node) -> float:
        ts = node.strat_state.times
        parent = node.parent
        if not ts or parent is None or not parent.strat_state.times:
            return 0.0
        lo, hi = StrategyBase._root_range(ctx)
        ch = _histogram(ts, lo, hi)
        ph = _histogram(parent.strat_state.times, lo, hi)
        return (1.0 - corr(ch, ph)) / 2.0


class _SiblingNormalized(StrategyBase):
    """Shared shape of the sibling-normalized root-correlation strategies
    (reference mcts_strategy_norm_anti_corr.hpp / mcts_strategy_norm_root_corr.hpp)."""

    SIGN = 1.0

    @classmethod
    def _raw(cls, ctx, node) -> float:
        ts = node.strat_state.times
        if not ts or ctx.root is None or not ctx.root.strat_state.times:
            return 0.0
        lo, hi = StrategyBase._root_range(ctx)
        ch = _histogram(ts, lo, hi)
        rh = _histogram(ctx.root.strat_state.times, lo, hi)
        return (1.0 + cls.SIGN * -corr(ch, rh)) / 2.0

    @classmethod
    def select(cls, ctx, node) -> float:
        raw = cls._raw(ctx, node)
        parent = node.parent
        if parent is None:
            return raw
        mx = max((cls._raw(ctx, s) for s in parent.children), default=0.0)
        return raw / mx if mx > 0 else raw


class NormAntiCorr(_SiblingNormalized):
    """Sibling-normalized anti-correlation vs the root histogram
    (reference mcts_strategy_norm_anti_corr.hpp, 111 lines)."""

    SIGN = 1.0


class NormRootCorr(_SiblingNormalized):
    """Sibling-normalized positive correlation vs the root histogram
    (reference mcts_strategy_norm_root_corr.hpp, 111 lines)."""

    SIGN = -1.0


class BalanceHistogram(StrategyBase):
    """Prefer the child most likely to fill the parent's least-filled time bin
    (reference mcts_strategy_balance_hist.hpp, 204 lines)."""

    @staticmethod
    def select(ctx, node) -> float:
        ts = node.strat_state.times
        parent = node.parent
        if not ts or parent is None or not parent.strat_state.times:
            return 0.0
        lo, hi = StrategyBase._root_range(ctx)
        ph = _histogram(parent.strat_state.times, lo, hi)
        target = ph.index(min(ph))
        ch = _histogram(ts, lo, hi)
        return ch[target] / len(ts)


ALL_STRATEGIES = {
    "random": Random,
    "unvisited": Unvisited,
    "fast_min": FastMin,
    "avg_time": AvgTime,
    "coverage": Coverage,
    "anti_corr": AntiCorrelation,
    "norm_anti_corr": NormAntiCorr,
    "norm_root_corr": NormRootCorr,
    "balance_hist": BalanceHistogram,
}
