"""MCTS search driver.

Parity target: reference ``tenzing-mcts/include/tenzing/mcts/mcts.hpp``
``explore`` (mcts.hpp:154-327): per iteration — select (rank 0), expand, random
rollout to a complete schedule, ``remove_redundant_syncs``, broadcast the order
to all hosts, provision events, benchmark on every host, backprop (rank 0),
periodic graphviz tree dump with decaying cadence (mcts.hpp:52-127,302-309),
phase counters (counters.hpp), stop when the root is fully visited
(mcts.hpp:194-201) — broadcast via the control plane's stop protocol.
"""

from __future__ import annotations

import random as _random
import sys
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Type

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    CachingBenchmarker,
    candidate_failed,
    result_row,
    schedule_id,
)
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.core.serdes import sequence_from_json, sequence_to_json
from tenzing_tpu.core.state import State
from tenzing_tpu.obs.progress import get_reporter
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.parallel.control_plane import ControlPlane, default_control_plane
from tenzing_tpu.solve.mcts.node import Node
from tenzing_tpu.solve.mcts.strategies import FastMin
from tenzing_tpu.utils import trap
from tenzing_tpu.utils.counters import Counters


@dataclass
class MctsOpts:
    """reference mcts::Opts (mcts.hpp:42-50)."""

    n_iters: int = 300
    bench_opts: BenchOpts = field(default_factory=BenchOpts)
    # multi-fidelity split (reference Benchmark::Opts knob, benchmarker.hpp:
    # 24-30 — the knob existed, the policy didn't): when ``screen_opts`` is
    # set, every rollout is measured at that CHEAP floor (search-time numbers
    # only steer the tree), and after the loop the ``confirm_topk`` best
    # distinct schedules are re-measured at the full ``bench_opts`` floor —
    # so the solver's official output carries final-fidelity numbers while
    # the tree explores at a fraction of the measurement cost (VERDICT r4
    # item 2: 40 rollouts in 93 s was 99.8% BENCHMARK)
    screen_opts: Optional[BenchOpts] = None
    confirm_topk: int = 6
    # informed playouts (Node.get_rollout): complete each rollout with this
    # ``(state, decisions) -> decision`` policy instead of uniform random,
    # taking a random decision with probability ``rollout_eps`` per step.
    # None = the reference's uniform-random playout.
    rollout_policy: Optional[object] = None
    rollout_eps: float = 0.15
    expand_rollout: bool = False
    dump_tree: bool = False
    dump_tree_prefix: str = "mcts_tree"
    dump_csv_path: Optional[str] = None
    seed: int = 0
    # equivalence-keyed benchmark cache: different rollouts that reduce (after
    # remove_redundant_syncs) to already-timed schedules reuse the recorded
    # result instead of recompiling and re-running (VERDICT r1 weak #5)
    cache_benchmarks: bool = True
    # fault.checkpoint.SearchCheckpoint: when set, rank 0 snapshots the
    # solver cursor (iteration, sims, tree size) after every iteration and
    # the trap handler writes a final snapshot — resume re-executes the
    # deterministic search against the journal-restored benchmark cache,
    # reconstructing the tree exactly (docs/robustness.md)
    checkpoint: Optional[object] = None
    # independent soundness gate (verify.ScheduleVerifier): every rollout —
    # i.e. the output of EventSynchronizer-driven construction PLUS
    # remove_redundant_syncs — is verified before it is benchmarked; an
    # unsound schedule is rejected like a failed compile (penalty backprop,
    # negative-cached) and a ``verify.unsound`` event lands in the trace.
    # Deterministic and device-free, so identical on every rank.
    verify: Optional[object] = None
    # compile prefetcher (bench.pipeline.PrefetchingBenchmarker): candidate
    # hints — the seed queue up front, speculative completions of the
    # expanded node's unplayed children per iteration, the confirm queue
    # before the sequential confirm loop — start background AOT compiles
    # while the foreground measurement runs.  Hints are advisory and consume
    # no search RNG: None (the default) is bit-identical to prefetch-off.
    prefetch: Optional[object] = None
    # how many speculative child completions to hint per iteration
    prefetch_rollouts: int = 2
    # disjoint fleet sharding ``(k, n)`` (search/fleet.py): restrict the
    # search to the k-th of n slices of the root's top-level children —
    # the enumeration is deterministic (Node.ensure_children sorts by
    # decision key), so n workers agree on the partition from their rank
    # alone, with no exchange.  An empty slice falls back to the single
    # child ``k % len`` so every worker always has a subtree.  None (the
    # default) searches the whole tree — bit-identical to pre-fleet.
    subtree: Optional[Tuple[int, int]] = None

    def to_json(self) -> dict:
        return {
            "n_iters": self.n_iters,
            "expand_rollout": self.expand_rollout,
            "seed": self.seed,
            "cache_benchmarks": self.cache_benchmarks,
        }


@dataclass
class SimResult:
    order: Sequence
    result: BenchResult
    # which measurement floor produced ``result``: "full" (bench_opts) or
    # "screen" (the cheap multi-fidelity floor) — recorded per CSV row so the
    # recorded-search databases stay honest about measurement regime
    fidelity: str = "full"


@dataclass
class MctsResult:
    sims: List[SimResult] = field(default_factory=list)
    tree_size: int = 0
    counters: Optional[Counters] = None

    def dump_csv(self, path: Optional[str] = None) -> str:
        rows = [
            # "full" rows keep the legacy 7+ops format; only screened rows
            # carry the explicit fidelity cell.  Numbered from 1: row 0 is
            # reserved for the naive-at-final-fidelity anchor (bench.py
            # --dump-csv), which a solver-internal dump does not have —
            # anchor readers then treat these files as anchorless
            result_row(i, s.result, s.order,
                       fidelity=None if s.fidelity == "full" else s.fidelity)
            for i, s in enumerate(self.sims, start=1)
        ]
        text = "\n".join(rows) + ("\n" if rows else "")
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def best(self) -> Optional[SimResult]:
        if not self.sims:
            return None
        return min(self.sims, key=lambda s: s.result.pct10)


def _dump_cadence(it: int) -> bool:
    """Decaying dump cadence (reference mcts.hpp:302-309): every iteration up to
    10, then every 10th up to 100, then every 100th."""
    if it < 10:
        return True
    if it < 100:
        return it % 10 == 0
    return it % 100 == 0


def _materialize_seed(root: Node, path) -> tuple:
    """Walk ``path`` (a decision list from ``solve.local.drive``) down the
    tree, creating ONLY the matching child per step (siblings are left for
    ``ensure_children`` to fill lazily when UCT actually visits the node — a
    ~100-decision path with eager sibling expansion would allocate thousands
    of never-selected Node/State clones); returns (deepest matched node, the
    terminal state reached by applying the FULL path).  Decisions match by
    content key — the same mechanism the hill-climb's neighbor replay uses —
    so a path recorded on an independent State chain of the same graph lands
    on the same tree nodes."""
    node, st = root, root.state
    matched = True
    for d in path:
        st = st.apply(d)
        if matched:
            nxt = next(
                (c for c in node.children
                 if c.decision is not None and c.decision.key() == d.key()),
                None,
            )
            if nxt is None and not node.expanded_ and not node.is_terminal():
                # pre-create just this child; expanded_ stays False so the
                # node's remaining decisions enumerate on first real visit
                nxt = Node(st, node.strategy, d, node)
                node.children.append(nxt)
            if nxt is None:
                matched = False
            else:
                node = nxt
    return node, st


def _seed_orders(graph: Graph, seeds, limit: int) -> list:
    """The terminal schedules of the first ``limit`` seed paths — known
    before the first iteration, so their compiles can prefetch while the
    incumbent measurements run.  Pure replay on fresh States (the same
    ``st.apply`` walk ``_materialize_seed`` performs): no tree, no RNG.
    ``limit`` (the prefetcher's queue bound) caps the replay work: hints
    beyond the queue would be dropped anyway, so materializing them is
    O(path_len) State.apply calls for nothing."""
    orders = []
    for path in seeds:
        if len(orders) >= limit:
            break
        st = State(graph)
        for d in path:
            st = st.apply(d)
        if st.is_terminal():
            orders.append(st.sequence)
    return orders


def _speculative_completions(node: Node, platform, prng, k: int,
                             skip: Optional[Node] = None) -> list:
    """Up to ``k`` plausible future rollouts for the compile prefetcher:
    complete the unplayed children of the just-expanded node to terminal
    schedules on THROWAWAY States with a forked RNG.

    Strictly side-effect-free with respect to the search: the tree is never
    touched (no ensure_children, no node creation), the search RNG is never
    consumed, and the (possibly stateful — bench.py's phase_policy carries a
    lane round-robin) rollout policy is never called — uniform-random
    completion only.  Misses are the prefetcher's ``wasted`` counter's job
    to account, not a correctness concern."""
    hints = []
    kids = [c for c in node.children
            if c.n_ == 0 and c is not skip] or [node]
    for child in kids[:k]:
        st = child.state
        while not st.is_terminal():
            ds = st.get_decisions(platform)
            if not ds:
                break
            st = st.apply(prng.choice(ds))
        if st.is_terminal():
            hints.append(remove_redundant_syncs(st.sequence))
    return hints


def prune_to_subtree(root: Node, platform, subtree: Tuple[int, int]) -> None:
    """Restrict ``root`` to the k-th of n rank-agreed top-level slices
    (``MctsOpts.subtree``): expand the root's children — a deterministic
    enumeration, identical in every process — and keep indices
    ``i % n == k % n``.  An empty slice degrades to the single child
    ``k % len(children)`` so a worker never ends up with nothing to
    search.  The kept children and everything below them are untouched:
    UCT statistics, seeds landing inside the slice, and the stop protocol
    all behave exactly as in a whole-tree search."""
    k, n = int(subtree[0]), max(1, int(subtree[1]))
    root.ensure_children(platform)
    kids = root.children
    if not kids:
        return
    keep = [c for i, c in enumerate(kids) if i % n == k % n]
    root.children = keep if keep else [kids[k % len(kids)]]


def explore(
    graph: Graph,
    platform,
    benchmarker,
    opts: Optional[MctsOpts] = None,
    strategy: Optional[Type] = None,
    control_plane: Optional[ControlPlane] = None,
    seeds=None,
) -> MctsResult:
    """Run the MCTS search (reference mcts::explore, mcts.hpp:154-327).

    ``seeds`` (optional): decision paths (e.g. recorded by
    ``solve.local.drive`` over heuristic incumbent policies) consumed as the
    FIRST iterations — each is materialized as a tree path, benchmarked like
    any rollout (usually a cache hit when the incumbent was pre-benchmarked),
    and backpropagated, warm-starting the selection statistics so UCT descends
    near known-good prefixes instead of re-discovering them from scratch
    (VERDICT r3 item 1).  Seeds ride the normal stop/schedule broadcast, so
    the multi-host protocol is unchanged."""
    opts = opts if opts is not None else MctsOpts()
    strategy = strategy if strategy is not None else FastMin
    cp = control_plane if control_plane is not None else default_control_plane()
    tr = get_tracer()
    tr.set_rank(cp.rank())
    reporter = get_reporter()
    rng = _random.Random(opts.seed)
    counters = Counters(prefix="mcts.phase")
    result = MctsResult(counters=counters)
    if opts.cache_benchmarks and not isinstance(benchmarker, CachingBenchmarker):
        # cache locally on every host: the broadcast order is identical on all
        # hosts, so hits/misses agree rank-to-rank (no divergent collectives)
        benchmarker = CachingBenchmarker(benchmarker)
    # a rank-coherent benchmarker (fault.resilient.ResilientBenchmarker, or
    # any wrapper forwarding its flag) guarantees every rank sees the same
    # failure at the same point, so the reject path is safe under a
    # multi-host control plane too — without it, a rank-local failure must
    # crash rather than desync the per-measurement barrier protocol
    reject_ok = cp.size() == 1 or getattr(benchmarker, "rank_coherent", False)

    def dump_partial():  # reference mcts.hpp:174-179
        if opts.dump_csv_path:
            result.dump_csv(opts.dump_csv_path)
        else:
            sys.stdout.write(result.dump_csv())
        if opts.checkpoint is not None and cp.rank() == 0:
            # the SIGINT final snapshot (ISSUE 3): the journal already holds
            # every completed measurement; this stamps the cursor so resume
            # tooling can report how far the interrupted run got
            opts.checkpoint.save_state(
                mcts={"n_sims": len(result.sims), "interrupted": True})

    trap.register_handler(dump_partial)
    # manual enter/exit (not `with`): the finally below must set the
    # run-total attrs on every exit path, including the mid-block return
    explore_ctx = tr.span("mcts.explore", n_iters=opts.n_iters,
                          seed=opts.seed)
    explore_sp = explore_ctx.__enter__()
    try:
        ctx = strategy.Context(seed=opts.seed)
        root = Node(State(graph), strategy) if cp.rank() == 0 else None
        if root is not None:
            ctx.root = root
            if opts.subtree is not None:
                prune_to_subtree(root, platform, opts.subtree)
        seed_iter = iter(seeds if seeds is not None else ())
        if opts.prefetch is not None and cp.rank() == 0 and seeds:
            # the seed queue's terminal schedules are known now; compile
            # them in the background while the first iterations measure
            opts.prefetch.prefetch(_seed_orders(
                graph, seeds, getattr(opts.prefetch, "depth", 8)))
        failed_keys: set = set()  # negative cache for uncompilable schedules
        for it in range(opts.n_iters):
            # per-iteration span (ISSUE 1): which node/path was selected,
            # the rolled-out schedule's hash, the measured time and the tree
            # size — the phase spans (mcts.phase.*) nest inside it
            with tr.span("mcts.iter", it=it) as it_sp:
                stop = False
                order: Optional[Sequence] = None
                endpoint: Optional[Node] = None
                if cp.rank() == 0:
                    assert root is not None
                    path = next(seed_iter, None)
                    if path is not None:
                        it_sp.set("seeded", True)
                        with counters.phase("SEED"):
                            endpoint, st = _materialize_seed(root, path)
                            if not st.is_terminal():  # defensive: complete
                                _, order = endpoint.get_rollout(
                                    platform, rng,
                                    policy=opts.rollout_policy,
                                    policy_eps=opts.rollout_eps,
                                )
                            else:
                                # benchmarked AS RECORDED (no redundant-sync
                                # cleanup): the cache key matches the incumbent's
                                # measurement exactly when the rollout opts do
                                # (with a multi-fidelity screen floor the seed is
                                # instead re-measured cheaply at that floor)
                                order = st.sequence
                    elif root.fully_visited_:
                        stop = True
                    else:
                        with counters.phase("SELECT"):
                            leaf = root.select(ctx, platform, rng)
                        with counters.phase("EXPAND"):
                            child = leaf.expand(platform, rng)
                        with counters.phase("ROLLOUT"):
                            endpoint, order = child.get_rollout(
                                platform, rng, opts.expand_rollout,
                                policy=opts.rollout_policy,
                                policy_eps=opts.rollout_eps,
                            )
                        with counters.phase("REDUNDANT_SYNC"):
                            order = remove_redundant_syncs(order)
                        if opts.prefetch is not None:
                            # expansion-children lookahead: speculative
                            # completions of the leaf's other unplayed
                            # children compile in the background while this
                            # rollout measures (forked RNG, throwaway
                            # States — the search itself is untouched)
                            opts.prefetch.prefetch(_speculative_completions(
                                leaf, platform,
                                _random.Random(
                                    f"prefetch:{opts.seed}:{it}"),
                                opts.prefetch_rollouts, skip=child))
                        if tr.enabled and child.decision is not None:
                            it_sp.set("selected", child.decision.desc())
                # stop-flag + schedule broadcast (mcts.hpp:129-152,244)
                with counters.phase("BCAST"):
                    stop = cp.bcast_json(stop)
                    if stop:
                        break
                    payload = cp.bcast_json(
                        sequence_to_json(order) if cp.rank() == 0 else None
                    )
                    if cp.rank() != 0:
                        order = sequence_from_json(payload, graph)
                # event provisioning (reference mcts.hpp:247-270)
                events = []
                for op in order:
                    if hasattr(op, "events"):
                        events.extend(op.events())
                platform.provision_events(events)
                key = canonical_key(order)
                if tr.enabled:
                    it_sp.set("schedule", schedule_id(order))
                ropts = opts.screen_opts if opts.screen_opts is not None else (
                    opts.bench_opts)
                res: Optional[BenchResult] = None
                if key not in failed_keys and opts.verify is not None:
                    verdict = opts.verify(order)
                    if not verdict.ok:
                        from tenzing_tpu.verify.soundness import report_unsound

                        report_unsound("mcts.rollout", order, verdict)
                        reporter.warn(
                            "mcts: rollout rejected by the soundness "
                            f"verifier ({verdict.witness()})", it=it)
                        it_sp.set("unsound", True)
                        failed_keys.add(key)
                if key not in failed_keys:
                    with counters.phase("BENCHMARK"):
                        try:
                            res = benchmarker.benchmark(order, ropts)
                        except Exception as e:
                            # a rollout whose schedule cannot compile/run on
                            # the hardware (e.g. liveness exceeding device
                            # memory) is a legitimate dead end, not a search
                            # crash.  Safe single-host, and multi-host when
                            # the benchmarker is rank-coherent (its agreement
                            # protocol made every rank fail together);
                            # otherwise a rank-local failure would desync the
                            # per-measurement barrier/allreduce protocol, so
                            # there the error must propagate (a crash beats a
                            # collective deadlock).  Device loss is never a
                            # per-candidate verdict: without a degradation
                            # fallback it must escalate out of the search.
                            from tenzing_tpu.fault.errors import DeviceLostError

                            if not reject_ok or isinstance(e, DeviceLostError):
                                raise
                            candidate_failed("mcts.rollout", order, e)
                            reporter.warn(
                                "mcts: rollout rejected (failed to compile/"
                                f"run: {type(e).__name__}: {str(e)[:200]})",
                                it=it,
                            )
                            failed_keys.add(key)
                if res is None:
                    # negative-cached or fresh failure: backprop a penalty
                    # (2x the worst time seen) so the tree learns to avoid
                    # the region without re-paying the failing compile; no
                    # sim is recorded (no fake measurements in the result
                    # set)
                    it_sp.set("rejected", True)
                    worst = max(
                        (s.result.pct50 for s in result.sims), default=1.0
                    )
                    pen = BenchResult.from_times([2.0 * worst])
                    if cp.rank() == 0:
                        with counters.phase("BACKPROP"):
                            endpoint.backprop(ctx, pen)
                    continue
                fidelity = ("screen" if opts.screen_opts is not None
                            else "full")
                if tr.enabled:
                    it_sp.set("pct50", res.pct50)
                    it_sp.set("fidelity", fidelity)
                result.sims.append(SimResult(
                    order=order, result=res, fidelity=fidelity,
                ))
                if cp.rank() == 0:
                    with counters.phase("BACKPROP"):
                        endpoint.backprop(ctx, res)
                    if tr.enabled:
                        it_sp.set("tree_size", root.size())
                    if opts.dump_tree and _dump_cadence(it):
                        path = f"{opts.dump_tree_prefix}_{it:06d}.dot"
                        with open(path, "w") as f:
                            f.write(root.dump_graphviz())
                    if opts.checkpoint is not None:
                        # cursor snapshot per completed iteration: the tree
                        # itself reconstructs on resume by re-executing the
                        # seeded search against the journal-restored cache
                        # (every answer identical, zero device time), so the
                        # checkpoint only needs the generative cursor
                        opts.checkpoint.save_state(
                            mcts={"it": it, "n_sims": len(result.sims),
                                  "tree_size": root.size()})
        # multi-fidelity confirm: the top-k distinct screened schedules are
        # re-measured at the full bench_opts floor so the solver's official
        # output carries final-fidelity numbers (the CachingBenchmarker key
        # includes the opts, so this cannot be answered from the screen
        # cache).  Rides the same broadcast protocol as rollouts — every
        # rank benchmarks every finalist.
        if opts.screen_opts is not None and result.sims:
            finals: List[Sequence] = []
            if cp.rank() == 0:
                seen_keys: set = set()
                for s in sorted(result.sims, key=lambda s: s.result.pct50):
                    k = canonical_key(s.order)
                    if k in seen_keys:
                        continue
                    seen_keys.add(k)
                    finals.append(s.order)
                    if len(finals) >= opts.confirm_topk:
                        break
                if opts.prefetch is not None:
                    # confirm-queue lookahead: finalists usually hit the
                    # program cache (they were measured during the search),
                    # but a resumed run's journal-answered rollouts never
                    # compiled — prefetch covers exactly that gap
                    opts.prefetch.prefetch(finals)
            with counters.phase("BCAST"):
                n_finals = cp.bcast_json(
                    len(finals) if cp.rank() == 0 else None)
            for fi in range(n_finals):
                with counters.phase("BCAST"):
                    payload = cp.bcast_json(
                        sequence_to_json(finals[fi]) if cp.rank() == 0
                        else None)
                order = (finals[fi] if cp.rank() == 0
                         else sequence_from_json(payload, graph))
                events = []
                for op in order:
                    if hasattr(op, "events"):
                        events.extend(op.events())
                platform.provision_events(events)
                with counters.phase("CONFIRM"):
                    try:
                        res = benchmarker.benchmark(order, opts.bench_opts)
                    except Exception as e:
                        from tenzing_tpu.fault.errors import DeviceLostError

                        if not reject_ok or isinstance(e, DeviceLostError):
                            raise
                        candidate_failed("mcts.confirm", order, e)
                        reporter.warn(
                            "mcts: confirm rejected (failed to compile/run: "
                            f"{type(e).__name__}: {str(e)[:200]})",
                            finalist=fi,
                        )
                        continue
                result.sims.append(
                    SimResult(order=order, result=res, fidelity="full"))
        if cp.rank() == 0 and root is not None:
            result.tree_size = root.size()
        if opts.dump_csv_path and cp.rank() == 0:
            result.dump_csv(opts.dump_csv_path)
        return result
    finally:
        explore_sp.set("n_sims", len(result.sims))
        explore_sp.set("tree_size", result.tree_size)
        explore_ctx.__exit__(None, None, None)
        trap.unregister_handler(dump_partial)
