"""Exhaustive depth-first schedule enumeration + benchmarking.

Parity target: reference ``tenzing-dfs`` (dfs.hpp/dfs.cpp): ``get_all_sequences``
is a worklist DFS over ``State.frontier`` with equivalence-class dedup at each
expansion (dfs.cpp:16-82); ``explore`` enumerates on rank 0, dedups completed
sequences pairwise under resource bijection (dfs.hpp:88-113), broadcasts each
schedule to all hosts (stop-flag + schedule, dfs.hpp:50-70,145-167), benchmarks
it, and collects results; SIGINT dumps the partial CSV (dfs.hpp:118-122).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    result_row,
    schedule_id,
)
from tenzing_tpu.core import sequence as sequence_mod
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import sequence_from_json, sequence_to_json
from tenzing_tpu.core.state import State
from tenzing_tpu.obs.progress import get_reporter
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.parallel.control_plane import ControlPlane, default_control_plane
from tenzing_tpu.utils import trap
from tenzing_tpu.utils.counters import Counters


@dataclass
class DfsOpts:
    """reference dfs::Opts (dfs.hpp:30-40; maxSeqs cap from examples/spmv.cu:117).

    ``batch=True`` benchmarks the whole enumerated set through
    ``benchmark_batch_times`` — every schedule visited once per iteration in a
    fresh random order (reference batch benchmark, benchmarker.cpp:21-76) — so
    slow system drift decorrelates from schedule identity and cross-schedule
    comparisons in the dumped database are honest.  Falls back to one-at-a-time
    benchmarking when the benchmarker has no ``benchmark_batch_times`` (e.g.
    CSV replay) or under a multi-host control plane (the batch path is
    single-host).

    ``prescreen`` (a ``learn.surrogate.SurrogateBenchmarker``) with
    ``prescreen_keep > 0`` ranks the enumerated terminals by predicted time
    and benchmarks only the best ``prescreen_keep`` — exhaustive enumeration
    with learned triage of the measurement budget (the skipped count lands
    in the ``learn.prune.dfs_skipped`` counter and the explore span)."""

    max_seqs: int = 15000
    bench_opts: BenchOpts = field(default_factory=BenchOpts)
    dump_csv_path: Optional[str] = None
    batch: bool = False
    batch_seed: int = 0
    prescreen: Optional[object] = None  # learn SurrogateBenchmarker
    prescreen_keep: int = 0
    # fault.checkpoint.SearchCheckpoint: rank 0 snapshots the frontier
    # cursor (next un-benchmarked terminal index) per measurement; resume
    # re-enumerates (deterministic) and the journal-restored cache answers
    # every already-measured terminal instantly (docs/robustness.md)
    checkpoint: Optional[object] = None
    # independent soundness gate (verify.ScheduleVerifier): every
    # enumerated terminal is verified before it is benchmarked; unsound
    # terminals are rejected with a ``verify.unsound`` event instead of
    # being measured (docs/robustness.md, "Schedule soundness")
    verify: Optional[object] = None
    # compile prefetcher (bench.pipeline.PrefetchingBenchmarker): the next
    # ``prefetch_lookahead`` terminals of the enumerated frontier are hinted
    # each iteration, so terminal i+1 compiles in the background while
    # terminal i measures (the batch path needs no hint here — a prefetcher
    # in the benchmark stack prefetches the whole batch itself).  Hints are
    # advisory; None (the default) is bit-identical to today.
    prefetch: Optional[object] = None
    prefetch_lookahead: int = 4
    # disjoint fleet sharding ``(k, n)`` (search/fleet.py): after
    # enumeration (+ prescreen), keep only terminals ``k % n, k % n + n,
    # ...`` of the deterministic enumeration order — n workers agree on
    # the partition from their rank alone, and the union of all n slices
    # is exactly the un-sharded terminal set.  An empty slice degrades to
    # the single terminal ``k % len`` so a worker always measures
    # something.  None (the default) is bit-identical to pre-fleet.
    subtree: Optional[tuple] = None

    def to_json(self) -> dict:
        """Provenance stamp of the options (reference dfs.cpp:11-14)."""
        return {"max_seqs": self.max_seqs, "n_iters": self.bench_opts.n_iters,
                "batch": self.batch, "batch_seed": self.batch_seed}


@dataclass
class SimResult:
    """One benchmarked schedule (reference SimResult, dfs.hpp:20-28)."""

    order: Sequence
    result: BenchResult


@dataclass
class DfsResult:
    """reference dfs::Result (dfs.hpp:74-76, dump_csv dfs.cpp:84-105)."""

    sims: List[SimResult] = field(default_factory=list)
    # phase-timing attribution (SELECT / DEDUP / BENCHMARK / BCAST) — the
    # MCTS result has carried this since the seed; DFS search time was
    # unattributable (ISSUE 1 satellite)
    counters: Optional[Counters] = None

    def dump_csv(self, path: Optional[str] = None) -> str:
        # numbered from 1: row index 0 is reserved for "the naive schedule
        # at final fidelity" (the bench.py --dump-csv anchor invariant) and
        # a solver-internal dump has no naive anchor — starting at 1 makes
        # anchor readers (recorded.naive_anchor_of, learn/dataset.py) treat
        # these files as anchorless instead of silently anchoring every
        # in-file ratio to an arbitrary first-enumerated terminal
        rows = [result_row(i, s.result, s.order)
                for i, s in enumerate(self.sims, start=1)]
        text = "\n".join(rows) + ("\n" if rows else "")
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def best(self) -> Optional[SimResult]:
        if not self.sims:
            return None
        return min(self.sims, key=lambda s: s.result.pct10)


def _dfs_terminals(
    graph: Graph, platform, max_seqs: int, dedup_terminals: bool,
    counters: Optional[Counters] = None,
) -> List[State]:
    """Worklist DFS over ``State.frontier`` (reference get_all_sequences,
    dfs.cpp:16-82; the per-expansion dedup is dfs.cpp:46-58).  With
    ``dedup_terminals`` the cap counts bijection-unique terminals, recognized
    by O(1) ``canonical_key`` lookups (equivalent to the reference's pairwise
    bijection scan — canonical keys are equal iff a lane/event bijection
    exists; agreement is property-tested in tests/test_dedup_canonical.py).

    ``counters`` attributes the walk per node: frontier expansion under
    SELECT, canonical-key dedup under DEDUP (spanless — a tracer span per
    node would flood the trace; the aggregate lands in the metrics)."""
    c = counters if counters is not None else Counters(mirror_global=False)
    terminals: List[State] = []
    seen_keys: set = set()
    stack: List[State] = [State(graph)]
    while stack and len(terminals) < max_seqs:
        st = stack.pop()
        if st.is_terminal():
            if dedup_terminals:
                with c.phase("DEDUP", span=False):
                    key = sequence_mod.canonical_key(st.sequence)
                    dup = key in seen_keys
                    seen_keys.add(key)
                if dup:
                    continue
            terminals.append(st)
            continue
        with c.phase("SELECT", span=False):
            stack.extend(st.frontier(platform))
    return terminals


def get_all_sequences(
    graph: Graph, platform, max_seqs: int = 15000,
    counters: Optional[Counters] = None,
) -> List[State]:
    """All complete schedules reachable from the initial state (terminal
    duplicates across converging DFS paths included; ``max_seqs`` caps raw
    terminals)."""
    return _dfs_terminals(graph, platform, max_seqs, dedup_terminals=False,
                          counters=counters)


def get_unique_sequences(
    graph: Graph, platform, max_seqs: int = 15000,
    counters: Optional[Counters] = None,
) -> List[State]:
    """Like :func:`get_all_sequences`, but terminals are deduplicated under
    resource bijection *as they are found* and ``max_seqs`` counts unique
    terminals — the same cap semantics as the native core
    (native/src/core.cpp enumerate_sequences), so ``TENZING_TPU_NATIVE=0``
    and ``=1`` see the same capped terminal set for the same budget."""
    return _dfs_terminals(graph, platform, max_seqs, dedup_terminals=True,
                          counters=counters)


def expand_all(graph: Graph) -> Graph:
    """Inline every CompoundOp.  An ExpandOp is the only decision available for
    a frontier compound and commutes with execution order, so eager expansion
    preserves the terminal-schedule space (reference state.cpp:82-87)."""
    while True:
        comps = [v for v in graph.vertices() if isinstance(v, CompoundOp)]
        if not comps:
            return graph
        graph = graph.clone_but_expand(comps[0])


def structural_variants(graph: Graph) -> List[Graph]:
    """All graphs reachable by compound expansion and choice substitution —
    the structural (graph-surgery) half of the decision space, taken eagerly so
    the order x lane half can run in the native core."""
    graph = expand_all(graph)
    choices = [v for v in graph.vertices() if isinstance(v, ChoiceOp)]
    if not choices:
        return [graph]
    out: List[Graph] = []
    for c in choices[0].choices():
        out.extend(structural_variants(graph.clone_but_replace(c, choices[0])))
    return out


def enumerate_schedules(graph: Graph, platform, max_seqs: int = 15000,
                        counters: Optional[Counters] = None) -> List[State]:
    """Terminal states with both per-expansion and terminal dedup applied.

    Structural decisions (compound expansion, implementation choices) are
    resolved eagerly into graph variants; each variant's order x lane space is
    enumerated by the native (C++) core when available, else by the Python
    path.  The ``max_seqs`` budget is fair-shared across variants (a huge first
    variant must not starve the others out of the search entirely); unused
    share flows to later variants.  Both paths count *deduplicated* terminals
    against the cap (same semantics either way; cross-checked in
    tests/test_native.py)."""
    from tenzing_tpu.native import bridge

    reporter = get_reporter()
    tr = get_tracer()
    variants = structural_variants(graph)
    out: List[State] = []
    for k, g in enumerate(variants):
        remaining = max_seqs - len(out)
        if remaining <= 0:
            reporter.warn(
                f"tenzing-tpu: dfs budget exhausted; {len(variants) - k} structural "
                "variant(s) not enumerated (raise max_seqs)",
                variants_left=len(variants) - k, max_seqs=max_seqs,
            )
            break
        share = -(-remaining // (len(variants) - k))  # ceil fair share
        with tr.span("dfs.enumerate_variant", variant=k, share=share) as sp:
            # the native core enumerates (and dedups) opaquely — its whole
            # wall is SELECT; the Python fallback self-attributes per node
            c = counters if counters is not None else Counters(
                mirror_global=False)
            with c.phase("SELECT", span=False):
                nat = bridge.try_enumerate(g, platform, share,
                                           dedup_terminals=True)
            if nat is None:
                nat = get_unique_sequences(g, platform, share,
                                           counters=counters)
            sp.set("n_terminals", len(nat))
        truncated = len(nat) >= share
        if truncated and k + 1 < len(variants):
            reporter.warn(
                f"tenzing-tpu: dfs variant {k} truncated at its fair share "
                f"({share} schedules)",
                variant=k, share=share,
            )
        out.extend(nat)
    return out


def _dedup_terminal_states(states: List[State]) -> List[State]:
    """Dedup of completed schedules under resource bijection (reference
    dfs.hpp:88-113) — by O(1) ``canonical_key`` bucket instead of the
    reference's O(n^2) pairwise bijection scan (equivalent by the canonical-key
    theorem, core/sequence.py; property-tested in
    tests/test_dedup_canonical.py)."""
    uniq: List[State] = []
    seen: set = set()
    for s in states:
        key = sequence_mod.canonical_key(s.sequence)
        if key not in seen:
            seen.add(key)
            uniq.append(s)
    return uniq


def explore(
    graph: Graph,
    platform,
    benchmarker,
    opts: Optional[DfsOpts] = None,
    control_plane: Optional[ControlPlane] = None,
) -> DfsResult:
    """Enumerate, dedup, benchmark every schedule (reference dfs::explore,
    dfs.hpp:78-178)."""
    import sys

    opts = opts if opts is not None else DfsOpts()
    cp = control_plane if control_plane is not None else default_control_plane()
    tr = get_tracer()
    tr.set_rank(cp.rank())
    reporter = get_reporter()
    counters = Counters(prefix="dfs.phase")
    result = DfsResult(counters=counters)
    batch_partial: dict = {}  # orders + in-flight times for mid-batch dumps

    def dump_partial():  # reference dfs.hpp:118-122
        if not result.sims and batch_partial:
            # signal arrived mid-batch: synthesize results from the times
            # accumulated so far (benchmark_batch_times fills times_out in
            # place) so a wall-clock-limited batch run still emits data
            for order, ts in zip(batch_partial["orders"], batch_partial["times"]):
                if ts:
                    result.sims.append(
                        SimResult(order=order, result=BenchResult.from_times(ts))
                    )
        if opts.dump_csv_path:
            result.dump_csv(opts.dump_csv_path)
        else:
            sys.stdout.write(result.dump_csv())
        if opts.checkpoint is not None and cp.rank() == 0:
            opts.checkpoint.save_state(
                dfs={"n_sims": len(result.sims), "interrupted": True})

    trap.register_handler(dump_partial)
    try:
        with tr.span("dfs.explore", max_seqs=opts.max_seqs,
                     batch=opts.batch) as root_sp:
            if cp.rank() == 0:
                with tr.span("dfs.enumerate"):
                    states = enumerate_schedules(graph, platform,
                                                 opts.max_seqs,
                                                 counters=counters)
                if (opts.prescreen is not None and opts.prescreen_keep > 0
                        and len(states) > opts.prescreen_keep):
                    # learned triage: benchmark only the terminals the
                    # surrogate ranks in the money (stable sort keeps the
                    # enumeration order as the tiebreak, so equal
                    # predictions stay deterministic)
                    with tr.span("learn.prescreen", n_in=len(states),
                                 keep=opts.prescreen_keep):
                        ranked = sorted(
                            range(len(states)),
                            key=lambda i: opts.prescreen.predict(
                                states[i].sequence)[0],
                        )
                        skipped = len(states) - opts.prescreen_keep
                        states = [states[i]
                                  for i in ranked[:opts.prescreen_keep]]
                    from tenzing_tpu.obs.metrics import get_metrics

                    get_metrics().counter("learn.prune.dfs_skipped").inc(
                        skipped)
                    reporter.info(
                        f"tenzing-tpu: dfs prescreen kept "
                        f"{len(states)}/{len(states) + skipped} terminals",
                        kept=len(states), skipped=skipped,
                    )
                if opts.subtree is not None and states:
                    sk, sn = int(opts.subtree[0]), max(1, int(opts.subtree[1]))
                    sliced = states[sk % sn::sn]
                    states = sliced if sliced else [states[sk % len(states)]]
                n = len(states)
            else:
                states, n = [], 0
            with counters.phase("BCAST"):
                n = cp.bcast_json(n)  # stop-flag protocol (dfs.hpp:50-70)
            root_sp.set("n_schedules", n)
            batch_times_fn = getattr(benchmarker, "benchmark_batch_times", None)
            if opts.batch and (batch_times_fn is None or cp.size() != 1):
                if cp.rank() == 0:
                    why = (
                        "multi-host control plane"
                        if cp.size() != 1
                        else f"{type(benchmarker).__name__} has no benchmark_batch_times"
                    )
                    reporter.warn(
                        f"tenzing-tpu: dfs batch=True ignored ({why}); falling back "
                        "to one-at-a-time (correlated) benchmarking",
                        why=why,
                    )
            if opts.batch and batch_times_fn is not None and cp.size() == 1:
                orders = [st.sequence for st in states]
                if opts.verify is not None:
                    from tenzing_tpu.verify.soundness import report_unsound

                    kept = []
                    for o in orders:
                        verdict = opts.verify(o)
                        if verdict.ok:
                            kept.append(o)
                            continue
                        report_unsound("dfs.benchmark", o, verdict)
                        reporter.warn(
                            "tenzing-tpu: dfs terminal rejected by the "
                            f"soundness verifier ({verdict.witness()})")
                    orders = kept
                times: List[List[float]] = [[] for _ in orders]
                batch_partial.update(orders=orders, times=times)
                # no explicit hint here: a prefetcher sitting in the
                # benchmark stack already prefetches the whole batch as the
                # first statement of its benchmark_batch_times forward
                # (bench/pipeline.py) — a second hint would be dead weight
                with counters.phase("BENCHMARK"):
                    batch_times_fn(
                        orders, opts.bench_opts, seed=opts.batch_seed,
                        times_out=times
                    )
                for order, ts in zip(orders, times):
                    result.sims.append(
                        SimResult(order=order, result=BenchResult.from_times(ts))
                    )
                # only after the results are in result.sims: a signal landing
                # between clear() and the copy would otherwise dump an empty CSV
                # despite every measurement having completed (trap.py contract)
                batch_partial.clear()
                if opts.checkpoint is not None and cp.rank() == 0:
                    opts.checkpoint.save_state(
                        dfs={"batch_done": True, "n_sims": len(result.sims)})
            else:
                # reject policy mirrors MCTS: a terminal that fails to
                # compile/run is a dead end, not a search crash — safe
                # single-host, and multi-host when the benchmarker's
                # rank-coherent agreement made every rank fail together
                reject_ok = cp.size() == 1 or getattr(
                    benchmarker, "rank_coherent", False)
                for i in range(n):
                    with tr.span("dfs.iter", i=i) as sp:
                        if cp.rank() == 0:
                            st = states[i]
                            payload = sequence_to_json(st.sequence)
                            if opts.prefetch is not None:
                                # frontier slice: the next terminals are
                                # known — compile them while this one
                                # measures.  Re-offering the window each
                                # iteration is cheap (id dedup) and lets
                                # hints dropped at a full queue resubmit.
                                opts.prefetch.prefetch(
                                    [states[j].sequence for j in range(
                                        i + 1,
                                        min(n, i + 1 +
                                            opts.prefetch_lookahead))])
                        else:
                            st, payload = None, None
                        with counters.phase("BCAST"):
                            payload = cp.bcast_json(payload)
                        if cp.rank() == 0:
                            order = st.sequence
                        else:
                            order = sequence_from_json(payload, graph)
                        if opts.verify is not None:
                            verdict = opts.verify(order)
                            if not verdict.ok:
                                from tenzing_tpu.verify.soundness import (
                                    report_unsound,
                                )

                                # deterministic + device-free: every rank
                                # reaches the same verdict, so the coherent
                                # skip needs no agreement round
                                report_unsound("dfs.benchmark", order,
                                               verdict)
                                reporter.warn(
                                    "tenzing-tpu: dfs terminal rejected by "
                                    "the soundness verifier "
                                    f"({verdict.witness()})", i=i)
                                sp.set("unsound", True)
                                continue
                        with counters.phase("BENCHMARK"):
                            try:
                                res = benchmarker.benchmark(
                                    order, opts.bench_opts)
                            except Exception as e:
                                from tenzing_tpu.fault.errors import (
                                    DeviceLostError,
                                )

                                # device loss is fatal, not a candidate
                                # verdict (fault/resilient.py escalation)
                                if not reject_ok or isinstance(
                                        e, DeviceLostError):
                                    raise
                                from tenzing_tpu.bench.benchmarker import (
                                    candidate_failed,
                                )

                                candidate_failed("dfs.benchmark", order, e)
                                reporter.warn(
                                    "tenzing-tpu: dfs terminal rejected "
                                    f"(failed to compile/run: "
                                    f"{type(e).__name__}: {str(e)[:200]})",
                                    i=i,
                                )
                                sp.set("rejected", True)
                                continue
                        if tr.enabled:
                            sp.set("schedule", schedule_id(order))
                            sp.set("pct50", res.pct50)
                        result.sims.append(SimResult(order=order, result=res))
                    # throttled: the cursor is consistency metadata (resume
                    # reconstructs from the journal, which has its own
                    # per-measurement fsync) — an atomic rewrite per
                    # terminal would double the sync I/O of the hot loop
                    if opts.checkpoint is not None and cp.rank() == 0 and (
                            i % 25 == 0 or i == n - 1):
                        opts.checkpoint.save_state(
                            dfs={"i": i, "n": n,
                                 "n_sims": len(result.sims)})
            if opts.dump_csv_path and cp.rank() == 0:
                result.dump_csv(opts.dump_csv_path)
            return result
    finally:
        trap.unregister_handler(dump_partial)
