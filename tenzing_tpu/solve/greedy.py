"""Greedy phase-ordered incumbent schedules.

The reference hard-codes one overlap discipline into its halo graph —
every-post-before-any-wait edges (ops_halo_exchange.cu:249-256).  This
framework's graphs deliberately leave that order free for the solver, and
:func:`greedy_phase_order` reconstructs the discipline as a *schedule* instead
of a graph constraint: ops execute in phase order (all packs, then all posts,
then all awaits, ...), round-robined across lanes, with the SDP machinery
inserting exactly the sync ops the solver would.  Anytime searches
(bench.py) seed their incumbent set with it so the directed search starts
from the domain heuristic rather than from naive.
"""

from __future__ import annotations

from typing import Sequence as Seq

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.sequence import Sequence


def greedy_phase_order(graph: Graph, platform, phases: Seq[str]) -> Sequence:
    """A complete schedule of ``graph`` executing ops in ``phases`` order.

    ``phases`` is a tuple of op-name prefixes, earliest first (must cover
    every op in the graph, including "start"/"finish"); an op's phase is the
    first prefix its name starts with.  Device ops round-robin across
    ``platform.lanes``; a later-phase op never runs while an earlier-phase op
    anywhere in the graph is unexecuted (the required sync is placed
    instead), so every phase-``k`` op happens before any phase-``k+1`` op on
    *all* lanes.  One implementation of the discipline: this is
    ``solve.local.drive`` under ``solve.local.phase_policy`` (which also
    resolves ChoiceOps and expands compounds for choice graphs)."""
    from tenzing_tpu.solve.local import drive, phase_policy

    seq, _ = drive(graph, platform, phase_policy(platform, phases))
    return seq
