"""Greedy phase-ordered incumbent schedules.

The reference hard-codes one overlap discipline into its halo graph —
every-post-before-any-wait edges (ops_halo_exchange.cu:249-256).  This
framework's graphs deliberately leave that order free for the solver, and
:func:`greedy_phase_order` reconstructs the discipline as a *schedule* instead
of a graph constraint: ops execute in phase order (all packs, then all posts,
then all awaits, ...), round-robined across lanes, with the SDP machinery
inserting exactly the sync ops the solver would.  Anytime searches
(bench.py) seed their incumbent set with it so the directed search starts
from the domain heuristic rather than from naive.
"""

from __future__ import annotations

from typing import Sequence as Seq

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.sequence import Sequence


def greedy_phase_order(graph: Graph, platform, phases: Seq[str]) -> Sequence:
    """A complete schedule of ``graph`` executing ops in ``phases`` order.

    ``phases`` is a tuple of op-name prefixes, earliest first (must cover
    every op in the graph, including "start"/"finish"); an op's phase is the
    first prefix its name starts with.  Device ops round-robin across
    ``platform.lanes``; a later-phase op never runs while an earlier-phase op
    anywhere in the graph is unexecuted (the required sync is placed
    instead), so every phase-``k`` op happens before any phase-``k+1`` op on
    *all* lanes."""
    from tenzing_tpu.core.state import AssignLane, ExecuteOp, State
    from tenzing_tpu.core.sync_ops import SyncOp

    def phase(op) -> int:
        name = op.name()
        for i, p in enumerate(phases):
            if name.startswith(p):
                return i
        return 0  # sync ops: only reachable via the fallback branch below

    st = State(graph)
    lane_rr = 0
    while not st.is_terminal():
        ds = st.get_decisions(platform)
        assigns = sorted(
            (d for d in ds if isinstance(d, AssignLane)), key=lambda d: d.op.name()
        )
        if assigns:
            # round-robin the alphabetically-first unassigned op onto lanes
            opname = assigns[0].op.name()
            lane = platform.lanes[lane_rr % len(platform.lanes)]
            lane_rr += 1
            # fall back to any offered AssignLane for the op if the round-robin
            # lane is not among the offered decisions (a platform may expose an
            # op on a lane subset; ADVICE r2)
            d = next(
                (d for d in assigns if d.op.name() == opname and d.lane == lane),
                assigns[0],
            )
            st = st.apply(d)
            continue
        execs = [d for d in ds if isinstance(d, ExecuteOp)]
        real = sorted(
            (d for d in execs if not isinstance(d.op, SyncOp)),
            key=lambda d: (phase(d.op), d.op.name()),
        )
        syncs = sorted(
            (d for d in execs if isinstance(d.op, SyncOp)), key=lambda d: d.op.desc()
        )
        # never run a later-phase op while an earlier-phase op anywhere in the
        # graph is still unexecuted (it is gated behind one of the offered
        # syncs): place the sync instead, keeping every phase-k op ahead of
        # every phase-k+1 op across *all* lanes
        done = {op.name() for op in st.sequence}
        pending_min = min(
            (phase(v) for v in st.graph.vertices() if v.name() not in done),
            default=99,
        )
        if real and (not syncs or phase(real[0].op) <= pending_min):
            st = st.apply(real[0])
            continue
        st = st.apply(syncs[0])
    return st.sequence
