"""THE corpus→surrogate training recipe.

One definition of ingestion → featurization → ridge-ensemble fit →
in-sample Spearman, shared by the two callers that used to carry copies:
``bench.py --learn-train`` (the driver's offline training branch,
bench/driver.py) and the serving warm path
(:meth:`~tenzing_tpu.serve.service.ScheduleService.warm` — the near
tier's pricing model).  A change to the training contract (corpus
admission, the min-rows threshold, the feature matrix call) lands in
both paths by construction instead of diverging the CLI-trained and
warm-trained surrogates.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.learn.dataset import Corpus
from tenzing_tpu.learn.features import FEATURE_NAMES
from tenzing_tpu.learn.model import RidgeEnsemble, spearman

# below this the bootstrap ensemble cannot even resample meaningfully —
# a model "trained" on 2-3 rows would predict noise with false confidence
MIN_TRAIN_ROWS = 4


def train_from_corpus(
    paths: List[str], graph, nbytes: Optional[Dict[str, int]] = None,
    trace_paths: Optional[List[str]] = None, log=None,
) -> Tuple[Optional[RidgeEnsemble], Dict[str, Any]]:
    """``(model, info)`` from recorded search databases.

    ``info`` always carries ``files``/``rows``; a corpus too small to
    trust adds ``error`` and returns ``model=None``, otherwise ``info``
    adds the in-sample ``train_spearman``.  ``nbytes`` must be the same
    buffer-size map the caller will featurize with at predict time
    (the train/serve feature contract, learn/features.py)."""
    corpus = Corpus.from_files(paths, graph, log=log)
    if trace_paths:
        corpus.attach_traces(trace_paths, log=log)
    info: Dict[str, Any] = {"files": len(paths), "rows": len(corpus.rows)}
    if len(corpus.rows) < MIN_TRAIN_ROWS:
        info["error"] = (
            f"corpus too small to train (< {MIN_TRAIN_ROWS} rows)")
        return None, info
    X, y = corpus.matrices(nbytes=nbytes)
    model = RidgeEnsemble(feature_names=list(FEATURE_NAMES))
    model.fit(X, y)
    pred, _ = model.predict(X)
    info["train_spearman"] = round(spearman(pred, y), 4)
    return model, info
