"""Learned schedule-cost surrogate trained on the measurement corpus.

The project's accumulated search databases (``bench.py --dump-csv``) and
telemetry bundles (``--trace-out``) are training data: this package turns
them into a model that predicts which schedules are fast *before* paying
the ~3.4 s compile+measure per candidate, and wires that prediction into
the solvers as a screen/confirm benchmarker.

Modules:

* :mod:`tenzing_tpu.learn.dataset` — corpus ingestion: CSV databases +
  trace bundles -> regime-normalized rows keyed by ``canonical_key``;
* :mod:`tenzing_tpu.learn.features` — deterministic schedule
  featurization (op mix, lane occupancy, comm bytes per engine, analytic
  makespan);
* :mod:`tenzing_tpu.learn.model` — pure-numpy ridge + bootstrap ensemble:
  prediction **and** uncertainty, JSON save/load;
* :mod:`tenzing_tpu.learn.surrogate` — ``SurrogateBenchmarker`` (model as
  a Benchmarker) and ``ScreeningBenchmarker`` (prescreen + escalate to the
  wrapped empirical benchmarker).

Workflow: ``docs/learn.md``.  CLI: ``bench.py --learn-train`` /
``--learn-model`` / ``--learn-screen``.
"""

from tenzing_tpu.learn.dataset import Corpus, CorpusRow
from tenzing_tpu.learn.features import FEATURE_NAMES, featurize
from tenzing_tpu.learn.model import RidgeEnsemble, spearman
from tenzing_tpu.learn.surrogate import (
    ScreeningBenchmarker,
    SurrogateBenchmarker,
)
from tenzing_tpu.learn.train import train_from_corpus

__all__ = [
    "Corpus",
    "CorpusRow",
    "FEATURE_NAMES",
    "RidgeEnsemble",
    "ScreeningBenchmarker",
    "SurrogateBenchmarker",
    "featurize",
    "spearman",
    "train_from_corpus",
]
