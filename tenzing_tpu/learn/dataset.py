"""Measurement-corpus ingestion: search databases -> normalized training rows.

Every search run archives its measurements twice — a per-schedule CSV
database (``bench.py --dump-csv``, naive as row 0 at final fidelity) and,
optionally, a replayable telemetry bundle (``--trace-out``, PR 1) whose
``bench.benchmark`` spans carry per-measurement provenance.  This module
turns a set of such archives into one normalized corpus:

* rows parse through the SAME machinery the replay benchmarker trusts
  (``CsvBenchmarker`` with ``split_fidelity`` — one definition of the wire
  format, bench/benchmarker.py) with ``strict=False`` so rows recorded
  against other structural variants skip instead of aborting the ingest;
* **labels are in-file paired ratios**: ``log(pct50 / anchor)`` against the
  file's own row-0 naive anchor (``naive_anchor_of``) — the regime
  normalization bench/recorded.py established for warm-start ranking.  Chip
  regimes swing >1.3x between runs, so absolute seconds from different
  files must never mix in one training set; the per-file ratio is
  regime-invariant and corpora from any number of runs concatenate;
* **only full-fidelity rows train**: a ``fid=screen`` row's pct50 came from
  a ~100x cheaper measurement floor than its file's anchor, so its ratio is
  not regime-honest (the same rule recorded.py applies);
* rows are **keyed by** ``core.sequence.canonical_key`` of the
  redundant-sync-normalized sequence — duplicate recordings of one program
  across files merge into a single row with the geometric-mean ratio.

Telemetry bundles join by the shared schedule-id convention
(``bench.benchmarker.schedule_id`` = ``obs.tracer.short_digest`` of the
serialized sequence): ``attach_traces`` counts each row's backing
``bench.benchmark`` spans, so corpus tooling can weigh or filter rows by how
much device evidence supports them.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import CsvBenchmarker, schedule_id
from tenzing_tpu.bench.recorded import naive_anchor_of
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer


@dataclass
class CorpusRow:
    """One distinct schedule with its regime-normalized label."""

    key: tuple                 # canonical_key of the normalized sequence
    seq: Sequence              # redundant-sync-normalized (featurize input)
    label: float               # log(pct50 / in-file naive anchor)
    pct50: float               # as recorded (absolute, regime-bound)
    anchor: float              # the file's naive anchor (absolute)
    source: str                # path of the database the row came from
    schedule: str = ""         # primary schedule_id digest (trace join key)
    # ALL as-recorded digests, one per duplicate recording merged into this
    # row: bijection-equivalent spellings (e.g. lanes 0/1 vs 1/0) hash to
    # different digests, and trace spans were tagged with whichever spelling
    # that run benchmarked — joins must try every one
    schedules: List[str] = field(default_factory=list)
    n_trace_measurements: int = 0  # bench.benchmark spans backing this row

    @property
    def ratio(self) -> float:
        """anchor / pct50 — the warm-start convention (>1 = beats naive)."""
        return math.exp(-self.label)


@dataclass
class Corpus:
    """Merged rows from any number of databases (see module docstring)."""

    rows: List[CorpusRow] = field(default_factory=list)
    n_files: int = 0
    n_skipped: int = 0         # unresolvable rows (strict=False skips)
    n_screen: int = 0          # screen-fidelity rows excluded from training
    n_merged: int = 0          # duplicate-schedule recordings merged away

    @classmethod
    def from_files(cls, paths: List[str], graph,
                   log: Optional[Callable[[str], None]] = None) -> "Corpus":
        """Ingest ``paths`` against ``graph``.  Files without a full-fidelity
        naive anchor contribute nothing (regime unknown — the recorded.py
        rule); unreadable files are reported and skipped."""
        tr = get_tracer()
        corpus = cls()
        by_key: Dict[tuple, List[CorpusRow]] = {}
        with tr.span("learn.ingest", n_files=len(paths)) as sp:
            for path in paths:
                try:
                    anchor = naive_anchor_of(path)
                    db = CsvBenchmarker.from_file(path, graph, strict=False,
                                                  normalize=True)
                except Exception as e:
                    if log:
                        log(f"learn corpus: {path} unreadable ({e})")
                    continue
                corpus.n_files += 1
                corpus.n_skipped += len(db.skipped)
                if anchor is None or anchor <= 0.0:
                    if log:
                        log(f"learn corpus: {path} has no naive anchor — "
                            "skipped (regime unknown)")
                    continue
                for (seq, res), fid in zip(db.entries, db.fidelities):
                    if fid != "full":
                        corpus.n_screen += 1
                        continue
                    if res.pct50 <= 0.0:
                        corpus.n_skipped += 1
                        continue
                    norm = remove_redundant_syncs(seq)
                    row = CorpusRow(
                        # the NORMALIZED sequence is the row: search-time
                        # queries featurize post-normalization (MCTS cleans
                        # every rollout; SurrogateBenchmarker.predict
                        # normalizes), so training on raw DFS dumps would
                        # skew the sync-count feature distribution between
                        # train and serve.  The trace-join digest stays on
                        # the sequence AS RECORDED — that is the form the
                        # bench.benchmark spans were tagged with.
                        key=canonical_key(norm),
                        seq=norm,
                        label=math.log(res.pct50 / anchor),
                        pct50=res.pct50,
                        anchor=anchor,
                        source=path,
                        schedule=schedule_id(seq),
                    )
                    row.schedules = [row.schedule]
                    by_key.setdefault(row.key, []).append(row)
            for key, dups in by_key.items():
                first = dups[0]
                if len(dups) > 1:
                    # geometric-mean ratio: one program recorded in several
                    # regimes averages in log space, where the per-file
                    # normalization made the labels commensurable
                    first.label = sum(r.label for r in dups) / len(dups)
                    # keep every duplicate's as-recorded digest: trace spans
                    # were tagged with the spelling each run benchmarked
                    seen_digests = set(first.schedules)
                    for r in dups[1:]:
                        if r.schedule not in seen_digests:
                            seen_digests.add(r.schedule)
                            first.schedules.append(r.schedule)
                    corpus.n_merged += len(dups) - 1
                corpus.rows.append(first)
            sp.set("n_rows", len(corpus.rows))
            sp.set("n_merged", corpus.n_merged)
        get_metrics().counter("learn.corpus.rows").inc(len(corpus.rows))
        if log:
            log(f"learn corpus: {corpus.n_files} files -> "
                f"{len(corpus.rows)} distinct rows "
                f"({corpus.n_merged} merged, {corpus.n_screen} screen-"
                f"fidelity excluded, {corpus.n_skipped} skipped)")
        return corpus

    def attach_traces(self, trace_paths: List[str],
                      log: Optional[Callable[[str], None]] = None) -> int:
        """Join telemetry bundles (``--trace-out`` JSONL) onto the corpus by
        schedule digest: each row's ``n_trace_measurements`` counts the
        ``bench.benchmark`` spans recorded for that schedule.  Returns the
        number of spans matched to a row."""
        from tenzing_tpu.obs.export import read_jsonl

        counts: Dict[str, int] = {}
        for path in trace_paths:
            try:
                records = read_jsonl(path)
            except Exception as e:
                if log:
                    log(f"learn corpus: trace {path} unreadable ({e})")
                continue
            for rec in records:
                if rec.get("kind") == "span" and (
                        rec.get("name") == "bench.benchmark"):
                    sid = (rec.get("attrs") or {}).get("schedule")
                    if sid:
                        counts[sid] = counts.get(sid, 0) + 1
        matched = 0
        for row in self.rows:
            n = sum(counts.get(sid, 0)
                    for sid in (row.schedules or [row.schedule]))
            row.n_trace_measurements += n
            matched += n
        if log and trace_paths:
            log(f"learn corpus: {matched} bench.benchmark spans joined from "
                f"{len(trace_paths)} trace files")
        return matched

    def matrices(self, nbytes: Optional[Dict[str, int]] = None,
                 env=None, cost_fn=None) -> Tuple["np.ndarray", "np.ndarray"]:
        """(X, y) training matrices: featurized rows and their log-ratio
        labels, row-aligned with ``self.rows``.  ``nbytes``/``env``/
        ``cost_fn`` must match what the search-time surrogate will
        featurize with (the feature-contract rule, learn/features.py)."""
        import numpy as np

        from tenzing_tpu.learn.features import featurize

        X = np.asarray(
            [featurize(r.seq, nbytes=nbytes, env=env, cost_fn=cost_fn)
             for r in self.rows],
            dtype=float,
        )
        y = np.asarray([r.label for r in self.rows], dtype=float)
        return X, y
