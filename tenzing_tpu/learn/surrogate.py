"""Surrogate benchmarkers: the trained model as a (screening) Benchmarker.

Two drop-ins for the Benchmarker protocol (``benchmark(order, opts) ->
BenchResult``):

* :class:`SurrogateBenchmarker` — answers every query from the model:
  device-free, microseconds per query.  Useful alone for offline search
  experiments (the CsvBenchmarker/AnalyticBenchmarker precedent) and as the
  prediction half of the screen.
* :class:`ScreeningBenchmarker` — the search-facing policy: predict first,
  **escalate to the wrapped empirical benchmarker only when the prediction
  is not enough** — when the query demands full fidelity, when the model is
  still uncalibrated for this run, or when the candidate plausibly ranks in
  the empirical top-k (the TACCL screen/confirm insight: a cheap prior
  collapses the search space; the expensive oracle confirms only the
  contenders).

Calibration: the model predicts ``log(t / anchor)`` in the *training*
regime, but each run's chip regime shifts absolute times by >1.3x.  The
screen self-calibrates online: every escalation yields (predicted,
measured); the running median residual becomes an additive log-space bias
correction, and the residual spread widens the escalation band — a model
that turns out wrong for this regime degrades to measuring everything
(correct, just not cheap) instead of silently mis-ranking.

Observability: ``learn.screen.surrogate_hits`` / ``learn.screen.escalations``
counters, the ``learn.screen.abs_log_err`` prediction-error histogram (post-
calibration, so it measures ranking error, not regime offset), the
``learn.screen.bias`` gauge, and a ``learn.screen`` trace event per decision
— model quality is visible in the Perfetto timeline next to the solver spans
(docs/learn.md, docs/observability.md).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import (
    BenchOpts,
    BenchResult,
    schedule_id,
)
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.utils.numeric import med, stddev


class SurrogateBenchmarker:
    """Model-only benchmarker: predicted time + uncertainty, no device.

    ``anchor_s`` maps the model's relative label back to seconds
    (``pct50 = anchor_s * exp(prediction)``); with the default 1.0 the
    returned "times" are relative to the training corpus's naive — fine for
    ranking, which is all a screen needs.  Predictions are cached by
    ``canonical_key``, the same equivalence every other benchmarker layer
    keys on."""

    def __init__(self, model, nbytes: Optional[Dict[str, int]] = None,
                 env=None, anchor_s: float = 1.0, cost_fn=None):
        self.model = model
        self.nbytes = dict(nbytes) if nbytes else {}
        self.env = env
        self.anchor_s = float(anchor_s)
        self.cost_fn = cost_fn
        self._cache: Dict[tuple, Tuple[float, float]] = {}

    def predict(self, order: Sequence) -> Tuple[float, float]:
        """(mean, std) of the predicted label ``log(t / anchor)``.

        The sequence is redundant-sync-normalized before featurization —
        the same equivalence the cache key uses, and the same normalization
        the corpus applies at train time (learn/dataset.py) — so two
        sync-layout spellings of one program cannot produce different
        feature vectors, and train/serve feature distributions agree."""
        from tenzing_tpu.core.schedule import remove_redundant_syncs

        norm = remove_redundant_syncs(order)
        return self.predict_normalized(norm, canonical_key(norm))

    def predict_normalized(self, norm: Sequence,
                           key: tuple) -> Tuple[float, float]:
        """:meth:`predict` for an already-normalized sequence with its
        canonical key precomputed — the screen's hot path normalizes once
        and shares the work instead of re-deriving it here."""
        got = self._cache.get(key)
        if got is None:
            from tenzing_tpu.learn.features import featurize

            mu, sigma = self.model.predict(
                featurize(norm, nbytes=self.nbytes, env=self.env,
                          cost_fn=self.cost_fn))
            got = self._cache[key] = (float(mu), float(sigma))
            get_metrics().counter("learn.surrogate.predictions").inc()
        return got

    def predicted_secs(self, order: Sequence) -> float:
        return self.anchor_s * math.exp(self.predict(order)[0])

    def benchmark(self, order: Sequence,
                  opts: Optional[BenchOpts] = None) -> BenchResult:
        mu, sigma = self.predict(order)
        t = self.anchor_s * math.exp(mu)
        lo = self.anchor_s * math.exp(mu - 2.0 * sigma)
        hi = self.anchor_s * math.exp(mu + 2.0 * sigma)
        return BenchResult(pct01=lo, pct10=lo, pct50=t, pct90=hi, pct99=hi,
                           stddev=t * sigma)


class ScreeningBenchmarker:
    """Surrogate-prescreen in front of an empirical benchmarker.

    Escalation policy, per query (first match wins):

    1. **fidelity** — ``screen_only_opts`` is set and the query's opts
       differ: full-fidelity queries (the MCTS confirm pass, the paired
       final) always measure; only the cheap screen floor may be answered
       from the model.
    2. **warmup** — fewer than ``escalate_topk`` empirical results so far:
       the bias correction needs residuals before predictions are
       trustworthy for this run's regime.
    3. **topk** — the calibrated optimistic bound ``mu + bias - z * (sigma
       + resid_sigma)`` reaches the k-th best empirical time seen: the
       candidate plausibly belongs in the top-k, so it earns a real
       measurement (anything the screen answers cheaply is, with
       confidence ~z, outside the money).

    Everything else returns the surrogate's (bias-corrected) prediction.
    ``hits`` / ``escalations`` count the split — the measurement-economy
    counters the acceptance gate asserts on."""

    def __init__(self, surrogate: SurrogateBenchmarker, inner,
                 escalate_topk: int = 8, z: float = 2.0,
                 screen_only_opts: Optional[BenchOpts] = None):
        self.surrogate = surrogate
        self.inner = inner
        self.escalate_topk = max(1, int(escalate_topk))
        self.z = float(z)
        self.screen_only_opts = screen_only_opts
        # model answers are deterministic and identical on every rank, so
        # the screen is exactly as rank-coherent as the benchmarker it
        # escalates to (fault/resilient.py's agreement protocol propagates
        # through wrappers via this attribute — solvers check it before
        # treating a multi-host benchmark failure as a reject)
        self.rank_coherent = getattr(inner, "rank_coherent", False)
        self.hits = 0          # surrogate-answered queries
        self.escalations = 0   # queries forwarded to the empirical inner
        self._deltas: List[float] = []   # log(measured) - log(predicted)
        self._bias = 0.0                 # running median of _deltas
        self._emp_logs: List[float] = []  # log pct50 of escalated results
        self._predicted: set = set()     # normalized keys answered by model

    def was_predicted(self, order: Sequence) -> bool:
        """True if a query equivalent to ``order`` was ever answered from
        the model rather than measured — dump paths use this to tag such
        rows ``fid=model`` so archived databases never pass predictions off
        as device measurements."""
        from tenzing_tpu.core.schedule import remove_redundant_syncs

        return canonical_key(remove_redundant_syncs(order)) in self._predicted

    def _escalation_reason(self, mu: float,
                           sigma: float,
                           opts: Optional[BenchOpts]) -> Optional[str]:
        if self.screen_only_opts is not None and (
                opts != self.screen_only_opts):
            return "fidelity"
        if len(self._emp_logs) < self.escalate_topk:
            return "warmup"
        resid = stddev(self._deltas) if len(self._deltas) > 1 else 0.0
        lcb = (math.log(self.surrogate.anchor_s) + mu + self._bias
               - self.z * (sigma + resid))
        kth = sorted(self._emp_logs)[self.escalate_topk - 1]
        if lcb <= kth:
            return "topk"
        return None

    def benchmark(self, order: Sequence,
                  opts: Optional[BenchOpts] = None) -> BenchResult:
        from tenzing_tpu.core.schedule import remove_redundant_syncs

        reg = get_metrics()
        tr = get_tracer()
        # one normalization + canonicalization per query, shared with the
        # surrogate's prediction cache and the provenance set
        norm = remove_redundant_syncs(order)
        key = canonical_key(norm)
        mu, sigma = self.surrogate.predict_normalized(norm, key)
        reason = self._escalation_reason(mu, sigma, opts)
        if reason is None:
            self.hits += 1
            reg.counter("learn.screen.surrogate_hits").inc()
            self._predicted.add(key)
            t = self.surrogate.anchor_s * math.exp(mu + self._bias)
            if tr.enabled:
                tr.event("learn.screen", schedule=schedule_id(order),
                         escalated=False, pct50=t, sigma=sigma)
            lo = t * math.exp(-2.0 * sigma)
            hi = t * math.exp(2.0 * sigma)
            return BenchResult(pct01=lo, pct10=lo, pct50=t, pct90=hi,
                               pct99=hi, stddev=t * sigma)
        self.escalations += 1
        reg.counter("learn.screen.escalations").inc()
        reg.counter(f"learn.screen.escalations.{reason}").inc()
        res = self.inner.benchmark(order, opts)
        # "fidelity" escalations measure at a DIFFERENT floor (the confirm
        # pass's full bench_opts, ~10-100x the screen floor) — their
        # absolute times belong to another measurement regime and must not
        # feed the screen-floor calibration: a confirm result in _deltas
        # would shift the bias gauge and fatten the abs_log_err histogram
        # with pure regime offset, and one in _emp_logs would poison the
        # top-k threshold the screen-floor LCBs compare against
        if reason != "fidelity" and res.pct50 > 0.0:
            delta = math.log(res.pct50) - (
                math.log(self.surrogate.anchor_s) + mu)
            # post-calibration error: how wrong the *corrected* prediction
            # was — the regime offset itself lands in the bias gauge
            reg.histogram("learn.screen.abs_log_err").observe(
                abs(delta - self._bias))
            self._deltas.append(delta)
            self._bias = med(self._deltas)
            reg.gauge("learn.screen.bias").set(self._bias)
            self._emp_logs.append(math.log(res.pct50))
        if tr.enabled:
            tr.event("learn.screen", schedule=schedule_id(order),
                     escalated=True, reason=reason, pct50=res.pct50)
        return res
