"""Ridge regression + bootstrap ensemble: prediction with uncertainty.

The corpus is small (hundreds to a few thousand distinct schedules) and the
features are low-dimensional summaries (learn/features.py), so the right
model is the simplest one that gives calibrated uncertainty: an ensemble of
ridge regressors, each fit on a bootstrap resample of the corpus.  The
ensemble mean is the prediction; the ensemble spread is the epistemic
uncertainty the screening policy escalates on (learn/surrogate.py) — a
schedule unlike anything in the corpus lands where the members disagree.

Pure numpy (already a dependency — no new deps per the build constraints),
closed-form normal-equation solve per member, JSON save/load carrying the
feature-name contract: loading a model refuses a featurizer whose names
drifted, so a stale model file fails loudly instead of silently
mis-predicting.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

import numpy as np


def spearman(a: Sequence[float], b: Sequence[float]) -> float:
    """Spearman rank correlation (Pearson over average ranks — ties get the
    mean of their positions, so duplicate predictions do not inflate the
    score).  The metric the acceptance gate is stated in: the surrogate's
    job is *ranking* schedules, not absolute timing."""
    a = np.asarray(a, dtype=float)
    b = np.asarray(b, dtype=float)
    if a.shape != b.shape or a.size < 2:
        raise ValueError("spearman needs two equal-length series, n >= 2")

    def ranks(x: np.ndarray) -> np.ndarray:
        order = np.argsort(x, kind="stable")
        r = np.empty(len(x), dtype=float)
        r[order] = np.arange(len(x), dtype=float)
        # average ties
        for v in np.unique(x):
            m = x == v
            if m.sum() > 1:
                r[m] = r[m].mean()
        return r

    ra, rb = ranks(a), ranks(b)
    sa, sb = ra.std(), rb.std()
    if sa == 0.0 or sb == 0.0:
        return 0.0
    return float(((ra - ra.mean()) * (rb - rb.mean())).mean() / (sa * sb))


class RidgeEnsemble:
    """Bootstrap ensemble of ridge regressors over standardized features.

    ``fit`` standardizes X column-wise and centers y, then solves
    ``(Z'Z + lam * n * I) w = Z'y`` per member on a seeded bootstrap
    resample; ``predict`` returns (mean, std) across members.  All state is
    plain arrays, so (de)serialization is a dict of lists."""

    def __init__(self, n_members: int = 16, ridge: float = 1e-3,
                 seed: int = 0,
                 feature_names: Optional[List[str]] = None):
        self.n_members = int(n_members)
        self.ridge = float(ridge)
        self.seed = int(seed)
        self.feature_names = list(feature_names) if feature_names else None
        self._mu: Optional[np.ndarray] = None   # feature means
        self._sigma: Optional[np.ndarray] = None  # feature stds (0 -> 1)
        self._y_mu: float = 0.0
        self._w: Optional[np.ndarray] = None    # (n_members, d)
        self.n_train: int = 0

    @property
    def fitted(self) -> bool:
        return self._w is not None

    def fit(self, X, y) -> "RidgeEnsemble":
        X = np.asarray(X, dtype=float)
        y = np.asarray(y, dtype=float)
        if X.ndim != 2 or len(X) != len(y) or len(y) < 2:
            raise ValueError("fit needs X (n, d) and y (n,), n >= 2")
        n, d = X.shape
        self._mu = X.mean(axis=0)
        sigma = X.std(axis=0)
        sigma[sigma == 0.0] = 1.0  # constant columns contribute nothing
        self._sigma = sigma
        self._y_mu = float(y.mean())
        Z = (X - self._mu) / self._sigma
        yc = y - self._y_mu
        rng = np.random.RandomState(self.seed)
        ws = []
        lam = self.ridge * n
        eye = np.eye(d)
        for _ in range(self.n_members):
            idx = rng.randint(0, n, size=n)
            Zi, yi = Z[idx], yc[idx]
            ws.append(np.linalg.solve(Zi.T @ Zi + lam * eye, Zi.T @ yi))
        self._w = np.stack(ws)
        self.n_train = n
        return self

    def predict(self, X) -> Tuple[np.ndarray, np.ndarray]:
        """(mean, std) of the ensemble's predictions, shape (n,) each."""
        if not self.fitted:
            raise RuntimeError("predict before fit/load")
        X = np.asarray(X, dtype=float)
        one = X.ndim == 1
        if one:
            X = X[None, :]
        Z = (X - self._mu) / self._sigma
        preds = Z @ self._w.T + self._y_mu  # (n, n_members)
        mean, std = preds.mean(axis=1), preds.std(axis=1)
        return (mean[0], std[0]) if one else (mean, std)

    # -- persistence -------------------------------------------------------

    def to_json(self) -> dict:
        if not self.fitted:
            raise RuntimeError("save before fit")
        return {
            "kind": "ridge_ensemble",
            "n_members": self.n_members,
            "ridge": self.ridge,
            "seed": self.seed,
            "n_train": self.n_train,
            "feature_names": self.feature_names,
            "mu": self._mu.tolist(),
            "sigma": self._sigma.tolist(),
            "y_mu": self._y_mu,
            "w": self._w.tolist(),
        }

    @classmethod
    def from_json(cls, j: dict,
                  expect_features: Optional[List[str]] = None
                  ) -> "RidgeEnsemble":
        if j.get("kind") != "ridge_ensemble":
            raise ValueError(f"not a ridge_ensemble model: {j.get('kind')!r}")
        names = j.get("feature_names")
        if expect_features is not None and (
                names is None or list(names) != list(expect_features)):
            # a model saved without names cannot prove it matches the
            # current featurizer — treat it as a mismatch rather than
            # skipping the check (the "fails loudly, never mis-predicts"
            # guarantee of the contract)
            raise ValueError(
                "model feature contract mismatch: saved "
                f"{'no' if names is None else len(names)} feature names, "
                f"featurizer has {len(expect_features)} — retrain against "
                "the current learn/features.py")
        m = cls(n_members=j["n_members"], ridge=j["ridge"], seed=j["seed"],
                feature_names=names)
        m._mu = np.asarray(j["mu"], dtype=float)
        m._sigma = np.asarray(j["sigma"], dtype=float)
        m._y_mu = float(j["y_mu"])
        m._w = np.asarray(j["w"], dtype=float)
        m.n_train = int(j.get("n_train", 0))
        return m

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f)

    @classmethod
    def load(cls, path: str,
             expect_features: Optional[List[str]] = None) -> "RidgeEnsemble":
        with open(path) as f:
            return cls.from_json(json.load(f), expect_features)
