"""Deterministic schedule featurization for the learned cost surrogate.

A schedule maps to a fixed-length float vector whose coordinates are named by
:data:`FEATURE_NAMES` — the feature contract the trained model is saved
against (model JSON carries the names; loading refuses a vector mismatch, so
a model trained under one featurizer version cannot silently mis-predict
under another).

Feature families ("Machine Learning for CUDA+MPI Design Rules", PAPERS.md —
the design-rule features there are exactly op-mix + placement + comm-volume
summaries of a schedule):

* **op-kind counts** — device ops, host data ops, each scheduler-inserted
  sync kind, each transfer-post kind (the vocabulary is the serdes
  ``KIND`` registry subset the search actually emits);
* **lane occupancy** — distinct lanes used, the busiest lane's device-op
  count, and the busy-lane fraction (1.0 = fully serial), the placement
  signal that separates overlapped from serialized schedules;
* **menu choices** — counts of kernel/engine suffix markers in op names
  (``.pallas`` / ``.xla`` / ``.rdma`` / ``.host`` / ``bf16``): which
  implementation the searched ChoiceOps resolved to;
* **comm bytes per engine** — bytes posted through the ICI vs the PCIe
  engine, classified by the SAME kind sets the analytic model queues on
  (bench/model.py ICI_KINDS/PCIE_KINDS);
* **analytic makespan** — the modeled makespan from
  :class:`~tenzing_tpu.bench.model.AnalyticBenchmarker` (raw and log), the
  strongest single prior: the learned model only has to fit the residual
  between the roofline model and the measured corpus.

Everything is a pure function of (sequence, nbytes map, ModelEnv) — no
randomness, no device — so feature vectors computed at train time and at
search time agree bit-for-bit.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from tenzing_tpu.bench.model import (
    ICI_KINDS,
    PCIE_KINDS,
    AnalyticBenchmarker,
    ModelEnv,
)
from tenzing_tpu.core.operation import BoundDeviceOp
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import SyncOp

# sync + transfer kinds counted individually (a stable, ordered vocabulary:
# appending here is a feature-contract change and invalidates saved models,
# which the names-check in learn/model.py turns into a loud load error)
_SYNC_KINDS = ("event_record", "wait_event", "event_sync", "lane_sync",
               "lane_wait")
_XFER_KINDS = ICI_KINDS + PCIE_KINDS + ("await_transfer", "multi_await")
# menu-choice markers in op names (the ChoiceOp resolution the search made)
_CHOICE_MARKS = (".pallas", ".xla", ".rdma", ".host", "bf16")
# searched-directive markers (ISSUE 10): the executed chunk/tile directives
# carry the solver's granularity decisions — without these coordinates the
# surrogate would score a chunked schedule identically to its unchunked
# twin and silently mis-rank both.  The strings are duplicated from
# core/chunking.py::CHUNK_MARK and runtime/fused.py::TILE_PREFIX so this
# featurizer stays import-light (tests/test_chunking.py asserts agreement).
_CHUNK_MARK = ".chunk.c"
_TILE_PREFIX = "fuse_tile.t"
# synthesized-collective directive marker + sketch vocabulary (ISSUE 17):
# the executed ``<base>.synth.<sketch>.c<K>`` directives carry which p2p
# decomposition the solver chose at each exchange site.  Duplicated from
# collectives/synth.py::SYNTH_MARK/SKETCHES for the same import-light
# reason (tests/test_collectives.py asserts agreement).
_SYNTH_MARK = ".synth."
_SYNTH_SKETCHES = ("ring", "ringr", "rhd", "neighbor", "pipe")

FEATURE_NAMES: List[str] = (
    ["n_ops", "n_device", "n_host_data", "n_sync"]
    + [f"n_{k}" for k in _SYNC_KINDS]
    + [f"n_{k}" for k in _XFER_KINDS]
    + ["n_lanes", "lane_max_occ", "serial_frac"]
    + [f"n_choice_{m.lstrip('.')}" for m in _CHOICE_MARKS]
    + ["ici_bytes", "pcie_bytes", "analytic_makespan", "log_analytic"]
    # APPEND-ONLY past this point: existing coordinates above must keep
    # their positions so corpora featurized before an append stay
    # consistent; a model saved under the shorter name list fails the
    # load contract loudly (learn/model.py) instead of mis-predicting
    + ["n_chunk_dir", "sum_chunk_counts", "n_fuse_tile_dir",
       "sum_fuse_tiles"]
    + [f"n_synth_{s}" for s in ("dir",) + _SYNTH_SKETCHES]
    + ["sum_synth_chunks"]
)


def _reads(op) -> List[str]:
    fn = getattr(op, "reads", None)
    return list(fn()) if callable(fn) else []


def featurize(
    seq: Sequence,
    nbytes: Optional[Dict[str, int]] = None,
    env: Optional[ModelEnv] = None,
    cost_fn=None,
) -> List[float]:
    """The feature vector of ``seq``, aligned with :data:`FEATURE_NAMES`.

    ``nbytes`` (buffer name -> byte size) feeds the comm-bytes features and
    the analytic-makespan feature; an empty/missing map degrades those to
    op-overhead-only estimates rather than failing — a corpus can be
    featurized before any buffers exist.  ``env``/``cost_fn`` configure the
    analytic model exactly as :class:`AnalyticBenchmarker` takes them — a
    workload with a custom per-op cost hook must featurize with the same
    hook or the makespan feature silently diverges between train and
    search."""
    nbytes = nbytes if nbytes is not None else {}
    bench = AnalyticBenchmarker(nbytes, env=env, cost_fn=cost_fn)
    kind_counts: Dict[str, int] = {k: 0 for k in _SYNC_KINDS + _XFER_KINDS}
    n_device = n_host_data = n_sync = 0
    lane_occ: Dict[int, int] = {}
    choice_counts = {m: 0 for m in _CHOICE_MARKS}
    ici_bytes = pcie_bytes = 0.0
    n_chunk_dir = sum_chunks = n_tile_dir = sum_tiles = 0
    n_synth_dir = sum_synth_chunks = 0
    synth_sketch_counts = {s: 0 for s in _SYNTH_SKETCHES}
    for op in seq:
        kind = getattr(op, "KIND", "")
        if kind in kind_counts:
            kind_counts[kind] += 1
        if isinstance(op, SyncOp):
            n_sync += 1
        elif isinstance(op, BoundDeviceOp):
            n_device += 1
            lid = op.lane().id
            lane_occ[lid] = lane_occ.get(lid, 0) + 1
        elif _reads(op) or (getattr(op, "writes", None)
                            and callable(op.writes) and op.writes()):
            n_host_data += 1
        name = op.name()
        for m in _CHOICE_MARKS:
            if m in name:
                choice_counts[m] += 1
        # searched-directive markers: count directives only (a partial's
        # name carries ".cNpJ", not the directive mark, so a chunked
        # schedule contributes one unit per chunked op, not per partial)
        i = name.rfind(_CHUNK_MARK)
        if i >= 0:
            try:
                sum_chunks += max(1, int(name[i + len(_CHUNK_MARK):]))
                n_chunk_dir += 1
            except ValueError:
                pass
        elif name.startswith(_TILE_PREFIX):
            try:
                sum_tiles += max(1, int(name[len(_TILE_PREFIX):]))
                n_tile_dir += 1
            except ValueError:
                pass
        # synth directives (``<base>.synth.<sketch>.c<K>``): like chunk
        # directives, count only the directive op, not the p2p steps (step
        # names carry ``<base>.<sketch><K>.`` prefixes, not the mark)
        j = name.rfind(_SYNTH_MARK)
        if j >= 0:
            sketch, sep, cpart = \
                name[j + len(_SYNTH_MARK):].rpartition(".c")
            if sep and sketch in synth_sketch_counts:
                try:
                    sum_synth_chunks += max(1, int(cpart))
                    synth_sketch_counts[sketch] += 1
                    n_synth_dir += 1
                except ValueError:
                    pass
        sz = float(sum(nbytes.get(n, 0) for n in _reads(op)))
        if kind in ICI_KINDS:
            ici_bytes += sz
        elif kind in PCIE_KINDS:
            pcie_bytes += sz
    makespan = bench.makespan(seq)
    lane_max = max(lane_occ.values(), default=0)
    out = [float(len(seq)), float(n_device), float(n_host_data),
           float(n_sync)]
    out += [float(kind_counts[k]) for k in _SYNC_KINDS]
    out += [float(kind_counts[k]) for k in _XFER_KINDS]
    out += [float(len(lane_occ)), float(lane_max),
            lane_max / n_device if n_device else 1.0]
    out += [float(choice_counts[m]) for m in _CHOICE_MARKS]
    out += [ici_bytes, pcie_bytes, makespan,
            math.log(max(makespan, 1e-12))]
    out += [float(n_chunk_dir), float(sum_chunks),
            float(n_tile_dir), float(sum_tiles)]
    out += [float(n_synth_dir)]
    out += [float(synth_sketch_counts[s]) for s in _SYNTH_SKETCHES]
    out += [float(sum_synth_chunks)]
    assert len(out) == len(FEATURE_NAMES)
    return out
