"""Schedule execution: lower a searched schedule to one compiled XLA program.

This is the TPU-native answer to the reference's dispatch model (SURVEY.md
§7.0/§7.2).  Where the reference *runs* each op at benchmark time — CUDA kernels
enqueued on ``cudaStream_t``, ordered by ``cudaEvent_t``
(benchmarker.cpp:83-119 hot loop, ops_cuda.cpp:48-130) — here the schedule's
happens-before structure is *traced into the HLO dependency graph* and XLA's
latency-hiding scheduler executes under exactly those constraints:

* each **lane** is a chain of ordering tokens: ops bound to the same lane are
  serialized in sequence order, ops on different lanes share no chain and may
  overlap (async DMA / collective / host-transfer overlap is XLA's to exploit);
* an **EventRecord** snapshots a lane's token; **WaitEvent** joins it into
  another lane's chain; **EventSync**/**LaneSync** join into the HOST chain —
  exact analogs of cudaEventRecord / cudaStreamWaitEvent / cudaEventSynchronize
  / cudaStreamSynchronize;
* **host ops** (CpuOp) form their own chain (host program order), and every
  device op joins the host token — a kernel cannot launch before prior host ops,
  matching CUDA dispatch semantics;
* **data dependencies are always honored**: buffers are SSA values in a dict, so
  a searched schedule cannot race — the token edges it chose are a superset of
  the graph's data edges (the reference achieves the same by the
  EventSynchronizer's construction, SURVEY.md §5).

Token realization — WHY NOT ``optimization_barrier``: measured on real TPU
hardware (v5e), the TPU backend *strips* ``opt-barrier`` during compilation
(post-optimization HLO contains zero ``opt-barrier`` instructions), so
barrier-chained schedules all lower to the same executable and timing is
schedule-independent.  Tokens here are therefore **real data dependencies** the
compiler cannot erase: a token is a finite float32 scalar derived from the
producer's output, and ``tie(x, t)`` computes ``x + select(t != t, t, 0)`` — a
value-preserving add (tokens are NaN-cleaned at creation so the select always
yields 0 at runtime) that XLA cannot constant-fold because proving the select
is zero would require value analysis it does not do.  Measured effect (64 MB
host-offload + 16x4096^3 bf16 matmul chain, TPU v5e): fully-serialized schedule
20.8 ms/iter (= sum of parts), 2-lane schedule 14.0 ms/iter (= overlap) — the
schedule space is physically real on hardware under this encoding.

Because each candidate schedule is its own compiled program, compile time is
excluded from measurement (compile once, cache by schedule JSON) and the
benchmarker fences with a device->host fetch per measurement (through a
remote-tunnel PJRT backend, ``block_until_ready`` alone does not fence;
see bench/benchmarker.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tenzing_tpu.core.operation import BoundDeviceOp, OpBase, unbound
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import sequence_to_json_str
from tenzing_tpu.obs.tracer import get_tracer, short_digest


def _scalarize(leaf) -> Any:
    """A float32 scalar data-dependent on ``leaf`` (its first element)."""
    x = jnp.asarray(leaf).reshape(-1)[0]
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        x = jnp.real(x)
    return x.astype(jnp.float32)


def _clean(t):
    """Scrub a token scalar to a finite value (select is opaque to constant
    folding).  Inf must go too: joins sum tokens, and inf + (-inf) = NaN would
    poison every downstream tie."""
    return lax.select(jnp.isfinite(t), t, jnp.zeros((), t.dtype))


def datatie(value, tok):
    """``value`` unchanged, but consumers now also wait for ``tok``.

    ``tok`` must be a cleaned (never-NaN) float32 scalar, so the select always
    takes the zero branch at runtime; the compiler cannot prove that, so the
    data edge survives TPU compilation (unlike ``optimization_barrier``).
    """
    z = lax.select(tok != tok, tok, jnp.zeros((), tok.dtype))
    if jnp.issubdtype(jnp.asarray(value).dtype, jnp.bool_):
        return jnp.logical_or(value, z != 0.0)
    return value + z.astype(jnp.asarray(value).dtype)


class TraceContext:
    """Mutable tracing state threaded through one schedule trace: the buffer
    dict (SSA), one token per lane, the host token, and one token per event.

    ``tokens`` (optional) seeds the chains — the benchmark loop carries token
    state across samples so a serialized schedule stays serialized from one
    sample to the next (the reference's cudaStream chains likewise persist
    across the hot loop's samples, benchmarker.cpp:93-99)."""

    def __init__(
        self,
        bufs: Dict[str, Any],
        axis_names=(),
        tokens: Optional[Dict[str, Any]] = None,
        host_space: Optional[set] = None,
    ):
        self.bufs = bufs
        self.axis_names = tuple(axis_names)
        # names of buffers resident in host memory: the TPU toolchain only
        # supports pure copies on host-space tensors (no arithmetic/slicing —
        # measured: host-side add/reshape/slice fail to compile), so ties,
        # awaits and fences must skip them
        self.host_space: set = set(host_space) if host_space else set()
        # in-flight transfers with an explicit completion handle: buffer name
        # -> closure(value) that blocks on the transfer's semaphores and
        # returns the completed value (split-kernel RDMA, ops/rdma.py).
        # Transient within one trace: the posting op stashes the closure, the
        # awaiting op settles it — a schedule always contains both, so nothing
        # here ever crosses the benchmark loop's carry.
        self.inflight: Dict[str, Any] = {}
        # int32 zero tied to the CURRENT op's token — set by trace_default
        # only for INDEX_TIE ops (None otherwise, so stale consumption by an
        # op outside the contract fails loudly)
        self.tok_index_zero: Any = None
        self._zero = jnp.zeros((), jnp.float32)
        if tokens is None:
            self._lane_tok: Dict[int, Any] = {}
            self._ev_tok: Dict[int, Any] = {}
            self._host_tok = self._zero
        else:
            self._lane_tok = dict(tokens["lanes"])
            self._ev_tok = dict(tokens["events"])
            self._host_tok = tokens["host"]

    def token_state(self) -> Dict[str, Any]:
        """The chains' current tips, in a fori_loop-carryable pytree."""
        return {
            "host": self._host_tok,
            "lanes": dict(self._lane_tok),
            "events": dict(self._ev_tok),
        }

    # -- token plumbing ----------------------------------------------------
    def _lane(self, lane: Lane):
        return self._lane_tok.get(lane.id, self._zero)

    def _join(self, *toks):
        toks = [t for t in toks if t is not None]
        if not toks:
            return self._zero
        out = toks[0]
        for t in toks[1:]:
            out = out + t
        return out

    def _tie(self, value, tok):
        """Value unchanged, but consumers now also wait for ``tok``."""
        return datatie(value, tok)

    def tie_named(self, name: str, value, tok):
        """Tie, unless ``name`` is host-resident (host-space tensors admit no
        arithmetic; ordering then rests on data dependencies alone)."""
        if name in self.host_space:
            return value
        return datatie(value, tok)

    # -- op tracing --------------------------------------------------------
    @staticmethod
    def _approx_nbytes(val) -> int:
        total = 0
        for l in jax.tree_util.tree_leaves(val):
            size = getattr(l, "size", None)
            dt = getattr(l, "dtype", None)
            if size is not None and dt is not None:
                total += int(size) * jnp.dtype(dt).itemsize
        return total

    def trace_default(self, op) -> None:
        """Trace a BoundOp: tie ONE of its reads to its chain token, apply,
        chain the written values back into the token."""
        is_device = isinstance(op, BoundDeviceOp)
        if is_device:
            tok_in = self._join(self._lane(op.lane()), self._host_tok)
        else:
            tok_in = self._host_tok
        tok_out = self._apply_op(op, tok_in)
        if is_device:
            self._lane_tok[op.lane().id] = tok_out
        else:
            self._host_tok = tok_out

    def trace_fused(self, op) -> None:
        """Trace a multi-lane fused-region op (runtime/fused.py): join EVERY
        member lane's chain plus the host chain, tie one read, apply the
        fused kernel, and advance ALL member lanes to the output token.

        Advancing every lane makes the fused region a conservative barrier
        across the lanes it absorbed — a strict superset of the ordering
        the member ops had individually, so replacing them with the fused
        op can never drop a happens-before edge (it can only add them; the
        cost is overlap the megakernel now owns internally)."""
        lanes = op.lanes()
        tok_in = self._join(*[self._lane(l) for l in lanes], self._host_tok)
        tok_out = self._apply_op(op, tok_in)
        for l in lanes:
            self._lane_tok[l.id] = tok_out

    def _apply_op(self, op, tok_in):
        """The shared tie-apply-writeback-join body of ``trace_default`` and
        ``trace_fused``: returns the output token (callers route it into
        the right chain(s)).

        One tied read is sufficient for the happens-before semantics — an op
        cannot start until EVERY input is ready, so making any one input
        depend on the token delays the whole op — and the SMALLEST read is
        tied so the value-preserving add never materializes on a huge buffer
        whose consumer XLA cannot slice-fuse (measured on the halo flagship:
        tying the 2 GB grid U on every unpack added a full grid read+write
        per direction — ~30 ms/iter of pure tie overhead)."""
        view = self.bufs
        # index-tie contract: an op declaring INDEX_TIE consumes
        # ``ctx.tok_index_zero`` (an int32 0 data-dependent on its token) in
        # its slice/update indices instead of receiving a value-tied read.
        # Same happens-before — the op cannot start before the token — but
        # the tie costs nothing: a value-add on a multi-GB grid read by six
        # ops forks the grid (measured on the halo flagship: 21 ms/iter of
        # fused full-grid adds + 13 ms of consequent non-in-place
        # dynamic-update-slices).
        from tenzing_tpu.core.operation import unbound

        if getattr(unbound(op), "INDEX_TIE", False):
            self.tok_index_zero = jnp.where(tok_in != tok_in, 1, 0).astype(
                jnp.int32
            )
        else:
            self.tok_index_zero = None  # stale-consumption guard
            reads = [n for n in op.reads() if n not in self.host_space]
            if reads:
                view = dict(self.bufs)
                name = min(reads, key=lambda n: (self._approx_nbytes(view[n]), n))
                view[name] = datatie(view[name], tok_in)
        out = op.apply(view, self)
        for name, val in out.items():
            if name not in self.bufs:
                raise KeyError(
                    f"op {op.desc()!r} writes undeclared buffer {name!r}; declare "
                    "it in the executor's initial buffers"
                )
            self.bufs[name] = val
        leaves = [
            l
            for name, val in out.items()
            if name not in self.host_space
            for l in jax.tree_util.tree_leaves(val)
        ]
        return self._join(tok_in, *[_clean(_scalarize(l)) for l in leaves])

    # -- sync-op hooks (core/sync_ops.py) ----------------------------------
    def record_event(self, lane: Lane, event: Event) -> None:
        self._ev_tok[event.id] = self._lane(lane)

    def wait_event(self, lane: Lane, event: Event) -> None:
        ev = self._ev_tok.get(event.id, self._zero)
        self._lane_tok[lane.id] = self._join(self._lane(lane), ev)

    def sync_event_host(self, event: Event) -> None:
        ev = self._ev_tok.get(event.id, self._zero)
        self._host_tok = self._join(self._host_tok, ev)

    def sync_lane_host(self, lane: Lane) -> None:
        self._host_tok = self._join(self._host_tok, self._lane(lane))

    def wait_lane(self, waiter: Lane, waitee: Lane) -> None:
        self._lane_tok[waiter.id] = self._join(self._lane(waiter), self._lane(waitee))


def evolve_host_space(names: set, op: OpBase) -> None:
    """Apply ONE op's transfer semantics to the host-space name set, in
    place: an op declaring ``DST_SPACE`` (ops/comm_ops.py) deterministically
    moves its writes into ("host") or out of ("device") host memory; every
    other op leaves the set untouched.  THE one copy of the space-evolution
    rule — ``TraceExecutor._host_space_after`` folds it over a schedule and
    the fusion partitioner (``runtime/fused.py::partition_regions``) steps
    it op-by-op while cutting regions, so a new memory space or a changed
    DST_SPACE convention lands in both or neither."""
    dst_space = getattr(unbound(op), "DST_SPACE", None)
    if dst_space is not None:
        for w in op.writes():
            if dst_space == "host":
                names.add(w)
            else:
                names.discard(w)


def _check_inflight_drained(tc: "TraceContext") -> None:
    """End-of-trace guard: a split-kernel transfer posted without a matching
    await would leave its wait closure in ``tc.inflight`` and downstream
    consumers would read an in-flight buffer on TPU — a *silent* data race.
    Every schedule the solvers emit pairs post with await (the graph contains
    both), so leftovers are a graph-construction bug; fail loudly (ADVICE r3)."""
    if tc.inflight:
        raise ValueError(
            "schedule ended with un-awaited in-flight transfers for buffers "
            f"{sorted(tc.inflight)}; every split-kernel post (e.g. "
            "RdmaCopyStart) needs a matching AwaitTransfer/MultiAwait in the "
            "schedule"
        )


class TraceExecutor:
    """Compiles schedules to XLA programs and runs them (the ``ScheduleRunner``
    the EmpiricalBenchmarker consumes).

    All buffer names must be declared in ``init_bufs``; when the platform has a
    mesh, the trace runs under ``shard_map`` with the platform's per-buffer
    partition specs, and comm ops may use collectives over the mesh axes.
    """

    def __init__(self, platform: Platform, init_bufs: Dict[str, Any]):
        self.platform = platform
        self.init_bufs = dict(init_bufs)
        self._cache: Dict[str, Callable] = {}
        # compile-provenance tallies (the driver's ``perf`` meta block):
        # programs actually traced+XLA-compiled by THIS process and the wall
        # seconds they took — cache hits (in-memory or the persistent
        # compile cache's fast path) are visible as cheap entries, never as
        # missing ones.  Guarded by a lock: the prefetch pipeline
        # (bench/pipeline.py) compiles on background threads.
        self.compile_count = 0
        self.compile_secs = 0.0
        self._stats_lock = threading.Lock()

    def _note_compile(self, secs: float) -> None:
        with self._stats_lock:
            self.compile_count += 1
            self.compile_secs += secs

    @staticmethod
    def place_host_buffers(bufs: Dict[str, Any], host_names) -> Dict[str, Any]:
        """jnp arrays for ``bufs`` with ``host_names`` device_put into
        pinned_host — the placement `_initial_host_space` detects (single
        shared helper for every workload's host-staged buffers)."""
        import jax
        import jax.numpy as jnp

        host_sh = jax.sharding.SingleDeviceSharding(
            jax.devices()[0], memory_kind="pinned_host"
        )
        host_names = set(host_names)
        return {
            k: jax.device_put(jnp.asarray(v), host_sh)
            if k in host_names
            else jnp.asarray(v)
            for k, v in bufs.items()
        }

    # -- build -------------------------------------------------------------
    def _initial_host_space(self) -> set:
        """Buffer names whose initial arrays live in host memory."""
        names = set()
        for k, v in self.init_bufs.items():
            mk = getattr(getattr(v, "sharding", None), "memory_kind", None)
            if mk is not None and "host" in str(mk):
                names.add(k)
        return names

    def _host_space_after(self, ops: List[OpBase]) -> set:
        """Host-space buffer names once the schedule has traced (transfer ops
        move names between spaces deterministically via DST_SPACE)."""
        names = self._initial_host_space()
        for op in ops:
            evolve_host_space(names, op)
        return names

    def _traced(self, ops: List[OpBase], bufs: Dict[str, Any]) -> Dict[str, Any]:
        tc = TraceContext(
            dict(bufs),
            axis_names=self.platform.axis_names,
            host_space=self._initial_host_space(),
        )
        for op in ops:
            op.trace(tc)
        _check_inflight_drained(tc)
        return tc.bufs

    @staticmethod
    def _token_template(ops: List[OpBase]) -> Dict[str, Any]:
        """Zero-token state covering every lane/event the schedule can touch —
        a stable carry structure for the benchmark loop."""
        zero = jnp.zeros((), jnp.float32)
        lanes: Dict[int, Any] = {}
        events: Dict[int, Any] = {}
        for op in ops:
            for l in getattr(op, "lanes", lambda: [])():
                lanes[l.id] = zero
            for e in getattr(op, "events", lambda: [])():
                events[e.id] = zero
        return {"host": zero, "lanes": lanes, "events": events}

    def _has_pallas(self, ops: List[OpBase]) -> bool:
        return any(getattr(op, "uses_pallas", lambda: False)() for op in ops)

    def _build(self, order: Sequence) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """The (unjitted) program for a schedule: trace, then shard_map over the
        platform mesh when present."""
        ops = order.vector()

        def fn(bufs: Dict[str, Any]) -> Dict[str, Any]:
            return self._traced(ops, bufs)

        mesh = self.platform.mesh
        if mesh is not None:
            specs = {name: self.platform.spec(name) for name in self.init_bufs}
            # check_vma=False only when a Pallas kernel is in the schedule: the
            # Pallas interpreter's internal slicing fails jax's varying-axes
            # check under shard_map (upstream limitation).  Plain-XLA schedules
            # keep the safety check on (ADVICE r1).
            kw = {"check_vma": False} if self._has_pallas(ops) else {}
            fn = jax.shard_map(
                fn, mesh=mesh, in_specs=(specs,), out_specs=specs, **kw
            )
        return fn

    def program(self, order: Sequence) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """The (unjitted) traced program for a schedule — the public surface
        for compile checks and external jitting (the driver's ``entry()``)."""
        return self._build(order)

    def compile(self, order: Sequence) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """One jitted program per schedule, cached by schedule JSON.

        The FIRST invocation of the returned callable — where jax.jit
        actually traces and XLA-compiles — is always timed into the
        ``compile_count``/``compile_secs`` tallies (the driver's ``perf``
        provenance), and additionally recorded as an ``executor.compile``
        span when tracing is enabled; steady-state calls pay one branch."""
        key = sequence_to_json_str(order)
        if key in self._cache:
            return self._cache[key]
        tr = get_tracer()
        sid = short_digest(key)
        with tr.span("executor.build", schedule=sid,
                     n_ops=len(order.vector())):
            jitted = jax.jit(self._build(order))
        state = {"cold": True}

        def wrapped(bufs: Dict[str, Any]) -> Dict[str, Any]:
            if state["cold"]:
                state["cold"] = False
                t0 = time.perf_counter()
                with get_tracer().span("executor.compile", schedule=sid):
                    out = jitted(bufs)
                self._note_compile(time.perf_counter() - t0)
                return out
            return jitted(bufs)

        self._cache[key] = wrapped
        return wrapped

    # -- run ---------------------------------------------------------------
    def run(self, order: Sequence) -> Dict[str, Any]:
        """Execute once and return the final buffers (numerical validation)."""
        return self.compile(order)(self.init_bufs)

    def prepare(self, order: Sequence) -> Callable[[], None]:
        """Fenced zero-arg runner for the benchmarker: dispatch + block."""
        f = self.compile(order)
        bufs = self.init_bufs

        def run_once() -> None:
            jax.block_until_ready(f(bufs))

        return run_once

    def prepare_n(self, order: Sequence) -> Callable[[int], None]:
        """Repeat-``n``-inside-one-program runner — the benchmark hot loop.

        The reference times ``for sample in 0..n: for op in order: op->run()``
        between two fences (benchmarker.cpp:83-119).  Here the sample loop is a
        ``fori_loop`` *inside* the compiled program carrying the buffer dict
        (ops re-run on their own outputs, exactly like the reference re-running
        ops on the same device buffers), and the fence is a ``device_get`` of
        one scalar reduced from every output buffer: through a remote-tunnel
        PJRT backend ``block_until_ready`` returns before execution finishes
        (measured: timing flat in n), so only a device->host fetch fences; the
        full-reduction fence also makes every op's output live (no dead-code
        narrowing of the final ops) and costs one pass *after* the loop,
        amortized over all n samples."""
        ops = order.vector()
        sched_json = sequence_to_json_str(order)
        key = "n:" + sched_json
        newly_built = key not in self._cache
        if not newly_built:
            f = self._cache[key]
        else:
            f = jax.jit(self._stepped_fn(ops))
            self._cache[key] = f
        bufs = self.init_bufs
        if not newly_built:
            def run_n(n: int) -> None:
                jax.device_get(f(bufs, jnp.int32(n))[0])

            return run_n
        # the first invocation of a newly-built program is where jax traces
        # and XLA compiles (device_get blocks through both) — time it into
        # the compile tallies, and (tracing enabled) record it as an
        # executor.compile span so trace bundles attribute compile wall
        # separately from steady-state measurement.  The id hashes the
        # UNPREFIXED schedule JSON so it matches the bench.benchmark span's
        # schedule_id for the same schedule.
        sid = short_digest(sched_json)
        state = {"cold": True}

        def run_n(n: int) -> None:
            if state["cold"]:
                state["cold"] = False
                t0 = time.perf_counter()
                with get_tracer().span("executor.compile", schedule=sid,
                                       n_samples=n):
                    jax.device_get(f(bufs, jnp.int32(n))[0])
                self._note_compile(time.perf_counter() - t0)
                return
            jax.device_get(f(bufs, jnp.int32(n))[0])

        return run_n

    def _stepped_fn(self, ops: List[OpBase]) -> Callable:
        """The (unjitted) repeat-n program ``stepped(bufs, n) -> (fence,
        host_outs)`` shared by :meth:`prepare_n` (lazy jit) and
        :meth:`precompile` (AOT): the fori_loop sample body carrying the
        buffer dict and token state, shard_mapped over the platform mesh
        when present, fenced by one reduced scalar."""
        axis_names = self.platform.axis_names
        tok0 = self._token_template(ops)
        host_space0 = self._initial_host_space()
        host_space_final = self._host_space_after(ops)

        def body(state):
            bufs, toks = state
            tc = TraceContext(
                dict(bufs), axis_names=axis_names, tokens=toks, host_space=host_space0
            )
            for op in ops:
                op.trace(tc)
            _check_inflight_drained(tc)
            return (tc.bufs, tc.token_state())

        mesh = self.platform.mesh

        def loop(bufs: Dict[str, Any], n) -> Dict[str, Any]:
            toks = tok0
            if mesh is not None:
                # comm ops make tokens shard-varying mid-loop; the carry
                # type must be varying from iteration 0
                toks = jax.tree_util.tree_map(
                    lambda t: lax.pcast(t, tuple(mesh.axis_names), to="varying"),
                    toks,
                )
            out, _ = lax.fori_loop(0, n, lambda i, s: body(s), (bufs, toks))
            return out

        if mesh is not None:
            # the whole sample loop runs inside one shard_map region: the
            # token carry is per-shard state (comm-op tokens vary across
            # mesh axes) and must not cross the shard_map boundary, where
            # it would need a replicated out_spec it cannot satisfy
            specs = {name: self.platform.spec(name) for name in self.init_bufs}
            from jax.sharding import PartitionSpec

            kw = {"check_vma": False} if self._has_pallas(ops) else {}
            loop = jax.shard_map(
                loop,
                mesh=mesh,
                in_specs=(specs, PartitionSpec()),
                out_specs=specs,
                **kw,
            )

        def stepped(bufs: Dict[str, Any], n) -> Any:
            out = loop(bufs, n)
            fence = jnp.zeros((), jnp.float32)
            host_outs = {}
            for name, val in out.items():
                if name in host_space_final:
                    # host-space tensors admit no arithmetic; returning
                    # them as program outputs keeps a trailing un-fetched
                    # spill alive (only the fence scalar is device_get)
                    host_outs[name] = val
                    continue
                for leaf in jax.tree_util.tree_leaves(val):
                    x = jnp.asarray(leaf)
                    if jnp.issubdtype(x.dtype, jnp.complexfloating):
                        x = jnp.real(x)
                    fence = fence + jnp.sum(x).astype(jnp.float32)
            return fence, host_outs

        return stepped

    # -- ahead-of-time compilation (the prefetch pipeline's entry point) ----
    def is_compiled(self, order: Sequence) -> bool:
        """True when the benchmark (repeat-n) program for ``order`` is
        already in the program cache (compiled or mid-first-invocation)."""
        return ("n:" + sequence_to_json_str(order)) in self._cache

    def precompile(self, order: Sequence) -> bool:
        """AOT-compile the benchmark program for ``order`` off the hot path:
        ``jax.jit(stepped).lower(init_bufs, n).compile()`` against the same
        buffer/token template :meth:`prepare_n` traces, cached under the
        same ``"n:"``-prefixed schedule-JSON key — so the foreground
        ``prepare_n``/``run_n`` (the measurement path) hit instead of
        compiling inline.  ``compile()``/``run()`` key the un-prefixed
        single-shot program and are NOT warmed by this (the integrity
        gate's ``run()`` still compiles its own program).

        Returns True when this call actually compiled, False on a cache hit.
        Thread-safe by design: meant to run on the prefetch pipeline's
        background workers (bench/pipeline.py) while the main thread
        measures — tracing is pure, XLA compilation releases the GIL, and
        the cache insert is a GIL-atomic ``setdefault`` (a racing duplicate
        compile is wasted work, never wrong results).  Touches NO platform
        state (``provision_events`` is per-candidate foreground bookkeeping
        the trace never reads), so a speculative precompile cannot perturb
        the search."""
        sched_json = sequence_to_json_str(order)
        key = "n:" + sched_json
        if key in self._cache:
            return False
        stepped = self._stepped_fn(order.vector())
        t0 = time.perf_counter()
        with get_tracer().span("executor.compile",
                               schedule=short_digest(sched_json), aot=True):
            compiled = jax.jit(stepped).lower(
                self.init_bufs, jnp.int32(1)).compile()
        self._note_compile(time.perf_counter() - t0)
        # first writer wins: a foreground prepare_n racing this insert keeps
        # its own (equivalent) program; both callables answer identically
        self._cache.setdefault(key, compiled)
        return True

    # -- timed execution mode (the attribution profiler's entry point) ------
    def op_stepped(self, order: Sequence):
        """Per-op stepped sub-programs — the attribution profiler's timed
        execution mode (obs/attrib/timeline.py).  Returns ``[(positions,
        fn)]`` covering every schedule position in order:

        * a sync op gets ``fn=None`` (token bookkeeping has no device work
          to time; its happens-before role is reconstructed by the analysis
          layer from the full op list);
        * every other op gets its own jitted ``fn(bufs) -> (fence, bufs)``
          sub-program tracing JUST that op against the buffer state the
          previous steps produced, fenced by a sum over the op's written
          buffers (full reduction, so the op's outputs stay live — the
          fence read is part of the step's measured cost and is documented
          as the stepped-mode bias in docs/observability.md);
        * split-kernel transfer posts (``rdma_copy_start`` /
          ``rdma_shift_start``) are grouped with everything through their
          matching ``await_transfer`` / ``multi_await`` into ONE step: the
          posted wait closure (``TraceContext.inflight``) cannot cross a
          jit trace boundary, so post→await is the smallest timeable unit.

        Mesh platforms are rejected: per-op stepping would have to carry
        shard-varying token state across program boundaries; multi-chip
        attribution goes through the xplane path (obs/attrib/xplane.py).
        """
        if self.platform.mesh is not None:
            raise RuntimeError(
                "op_stepped: per-op stepped profiling is single-chip only "
                "(use obs/attrib/xplane.py jax.profiler capture on meshes)")
        ops = order.vector()
        steps = []
        cur: List[int] = []
        pending: set = set()
        for p, op in enumerate(ops):
            if getattr(op, "is_sync", lambda: False)():
                if cur:
                    cur.append(p)  # keep position; trace skips it
                else:
                    steps.append(((p,), None))
                continue
            cur.append(p)
            kind = getattr(op, "KIND", "")
            if kind in ("rdma_copy_start", "rdma_shift_start"):
                pending.update(op.writes())
            elif kind == "await_transfer":
                pending.discard(op.buf())
            elif kind == "multi_await":
                pending.difference_update(op.bufs())
            if not pending:
                steps.append((tuple(cur), self._op_step_fn(ops, tuple(cur))))
                cur = []
        if cur:  # un-awaited tail: still timeable as one group
            steps.append((tuple(cur), self._op_step_fn(ops, tuple(cur))))
        return steps

    def _op_step_fn(self, ops: List[OpBase], positions) -> Callable:
        """The jitted sub-program for one stepped group: trace the group's
        non-sync ops with a fresh TraceContext (steps run to completion
        before the next starts, so zero token seeds are exact) and fence on
        a full reduction of the group's written device-space buffers."""
        group = [ops[p] for p in positions
                 if not getattr(ops[p], "is_sync", lambda: False)()]
        host_space0 = self._host_space_after(ops[: positions[0]])
        host_space_after = self._host_space_after(ops[: positions[-1] + 1])
        axis_names = self.platform.axis_names
        written = [n for op in group
                   for n in (op.writes() if hasattr(op, "writes") else [])]
        fence_names = [n for n in dict.fromkeys(written)
                       if n not in host_space_after]

        def fn(bufs: Dict[str, Any]) -> Any:
            tc = TraceContext(dict(bufs), axis_names=axis_names,
                              host_space=set(host_space0))
            for op in group:
                op.trace(tc)
            _check_inflight_drained(tc)
            fence = jnp.zeros((), jnp.float32)
            for name in fence_names:
                for leaf in jax.tree_util.tree_leaves(tc.bufs[name]):
                    x = jnp.asarray(leaf)
                    if jnp.issubdtype(x.dtype, jnp.complexfloating):
                        x = jnp.real(x)
                    fence = fence + jnp.sum(x).astype(jnp.float32)
            return fence, tc.bufs

        return jax.jit(fn)

    def lowered_text(self, order: Sequence) -> str:
        """Lowered (pre-optimization) HLO of a schedule (debugging / tests)."""
        return jax.jit(self._build(order)).lower(self.init_bufs).as_text()

    def compiled_text(self, order: Sequence) -> str:
        """Post-optimization HLO — what actually runs; the token data edges
        must still be visible here (the whole point of ``datatie``)."""
        return jax.jit(self._build(order)).lower(self.init_bufs).compile().as_text()
