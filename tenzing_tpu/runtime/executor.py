"""Schedule execution: lower a searched schedule to one compiled XLA program.

This is the TPU-native answer to the reference's dispatch model (SURVEY.md
§7.0/§7.2).  Where the reference *runs* each op at benchmark time — CUDA kernels
enqueued on ``cudaStream_t``, ordered by ``cudaEvent_t``
(benchmarker.cpp:83-119 hot loop, ops_cuda.cpp:48-130) — here the schedule's
happens-before structure is *traced into the HLO dependency graph* and XLA's
latency-hiding scheduler executes under exactly those constraints:

* each **lane** is a chain of ``optimization_barrier`` tokens: ops bound to the
  same lane are serialized in sequence order, ops on different lanes share no
  chain and may overlap (kernel/DMA/collective overlap is XLA's to exploit);
* an **EventRecord** snapshots a lane's token; **WaitEvent** joins it into
  another lane's chain; **EventSync**/**LaneSync** join into the HOST chain —
  exact analogs of cudaEventRecord / cudaStreamWaitEvent / cudaEventSynchronize
  / cudaStreamSynchronize;
* **host ops** (CpuOp) form their own chain (host program order), and every
  device op joins the host token — a kernel cannot launch before prior host ops,
  matching CUDA dispatch semantics;
* **data dependencies are always honored**: buffers are SSA values in a dict, so
  a searched schedule cannot race — the token edges it chose are a superset of
  the graph's data edges (the reference achieves the same by the
  EventSynchronizer's construction, SURVEY.md §5).

Because each candidate schedule is its own compiled program, compile time is
excluded from measurement (compile once, cache by schedule JSON) and the
benchmarker fences with ``block_until_ready`` per measurement — SURVEY.md §7.2
"Measurement fidelity".
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from tenzing_tpu.core.operation import BoundDeviceOp, OpBase
from tenzing_tpu.core.platform import Platform
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.serdes import sequence_to_json_str


def _barrier(values):
    return jax.lax.optimization_barrier(values)


class TraceContext:
    """Mutable tracing state threaded through one schedule trace: the buffer
    dict (SSA), one token per lane, the host token, and one token per event."""

    def __init__(self, bufs: Dict[str, Any], axis_names=()):
        self.bufs = bufs
        self.axis_names = tuple(axis_names)
        self._zero = jnp.zeros((), jnp.float32)
        self._lane_tok: Dict[int, Any] = {}
        self._ev_tok: Dict[int, Any] = {}
        self._host_tok = self._zero

    # -- token plumbing ----------------------------------------------------
    def _lane(self, lane: Lane):
        return self._lane_tok.get(lane.id, self._zero)

    def _join(self, *toks):
        toks = [t for t in toks if t is not None]
        if len(toks) == 1:
            return toks[0]
        return _barrier(tuple(toks))[0]

    def _tie(self, value, tok):
        """Value unchanged, but consumers now also wait for ``tok``."""
        return _barrier((value, tok))[0]

    # -- op tracing --------------------------------------------------------
    def trace_default(self, op) -> None:
        """Trace a BoundOp: tie its reads to its chain token, apply, chain the
        written values back into the token."""
        is_device = isinstance(op, BoundDeviceOp)
        if is_device:
            tok_in = self._join(self._lane(op.lane()), self._host_tok)
        else:
            tok_in = self._host_tok
        view = self.bufs
        reads = op.reads()
        if reads:
            view = dict(self.bufs)
            for name in reads:
                view[name] = self._tie(view[name], tok_in)
        out = op.apply(view, self)
        for name, val in out.items():
            if name not in self.bufs:
                raise KeyError(
                    f"op {op.desc()!r} writes undeclared buffer {name!r}; declare "
                    "it in the executor's initial buffers"
                )
            self.bufs[name] = val
        leaves = jax.tree_util.tree_leaves(out)
        tok_out = _barrier(tuple([tok_in] + leaves))[0] if leaves else tok_in
        if is_device:
            self._lane_tok[op.lane().id] = tok_out
        else:
            self._host_tok = tok_out

    # -- sync-op hooks (core/sync_ops.py) ----------------------------------
    def record_event(self, lane: Lane, event: Event) -> None:
        self._ev_tok[event.id] = self._lane(lane)

    def wait_event(self, lane: Lane, event: Event) -> None:
        ev = self._ev_tok.get(event.id, self._zero)
        self._lane_tok[lane.id] = self._join(self._lane(lane), ev)

    def sync_event_host(self, event: Event) -> None:
        ev = self._ev_tok.get(event.id, self._zero)
        self._host_tok = self._join(self._host_tok, ev)

    def sync_lane_host(self, lane: Lane) -> None:
        self._host_tok = self._join(self._host_tok, self._lane(lane))

    def wait_lane(self, waiter: Lane, waitee: Lane) -> None:
        self._lane_tok[waiter.id] = self._join(self._lane(waiter), self._lane(waitee))


class TraceExecutor:
    """Compiles schedules to XLA programs and runs them (the ``ScheduleRunner``
    the EmpiricalBenchmarker consumes).

    All buffer names must be declared in ``init_bufs``; when the platform has a
    mesh, the trace runs under ``shard_map`` with the platform's per-buffer
    partition specs, and comm ops may use collectives over the mesh axes.
    """

    def __init__(self, platform: Platform, init_bufs: Dict[str, Any]):
        self.platform = platform
        self.init_bufs = dict(init_bufs)
        self._cache: Dict[str, Callable] = {}

    # -- build -------------------------------------------------------------
    def _traced(self, ops: List[OpBase], bufs: Dict[str, Any]) -> Dict[str, Any]:
        tc = TraceContext(dict(bufs), axis_names=self.platform.axis_names)
        for op in ops:
            op.trace(tc)
        return tc.bufs

    def _build(self, order: Sequence) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """The (unjitted) program for a schedule: trace, then shard_map over the
        platform mesh when present."""
        ops = order.vector()

        def fn(bufs: Dict[str, Any]) -> Dict[str, Any]:
            return self._traced(ops, bufs)

        mesh = self.platform.mesh
        if mesh is not None:
            specs = {name: self.platform.spec(name) for name in self.init_bufs}
            # check_vma=False: the Pallas interpreter's internal slicing fails
            # jax's varying-axes check under shard_map (upstream limitation);
            # data deps are already guaranteed by the SSA buffer dict
            fn = jax.shard_map(
                fn, mesh=mesh, in_specs=(specs,), out_specs=specs, check_vma=False
            )
        return fn

    def compile(self, order: Sequence) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """One jitted program per schedule, cached by schedule JSON."""
        key = sequence_to_json_str(order)
        if key in self._cache:
            return self._cache[key]
        jitted = jax.jit(self._build(order))
        self._cache[key] = jitted
        return jitted

    # -- run ---------------------------------------------------------------
    def run(self, order: Sequence) -> Dict[str, Any]:
        """Execute once and return the final buffers (numerical validation)."""
        return self.compile(order)(self.init_bufs)

    def prepare(self, order: Sequence) -> Callable[[], None]:
        """Fenced zero-arg runner for the benchmarker: dispatch + block."""
        f = self.compile(order)
        bufs = self.init_bufs

        def run_once() -> None:
            jax.block_until_ready(f(bufs))

        return run_once

    def lowered_text(self, order: Sequence) -> str:
        """Lowered HLO of a schedule (debugging / tests)."""
        return jax.jit(self._build(order)).lower(self.init_bufs).as_text()
