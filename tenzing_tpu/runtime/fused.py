"""Megakernel fusion backend: lower a searched schedule into fused Pallas
regions with searchable tiling.

The searched win has been bounded by per-op dispatch: ``runtime/executor.py``
traces each op separately and serializes them with ordering tokens, and the
attribution profiler measures exactly what that costs
(``dispatch_overhead_us = sum_of_parts - measured`` — the MPK baseline
number, obs/attrib/analysis.py).  MPK (PAPERS.md) shows that lowering a
*complete* schedule into one megakernel, and T3 that tiling ops so a
transfer overlaps its producer/consumer, moves the optimization *inside*
the fused program.  This module is that lowering:

* :func:`partition_regions` cuts a complete schedule into **fusible
  regions**: maximal runs of fusible device ops between comm/host/sync
  boundaries.  A comm or host op splits (transfers and collectives cannot
  live inside a Pallas kernel body); a cross-lane sync splits (an incoming
  wait means a member would have to observe non-member progress
  mid-region); an ``EventRecord`` interleaved inside a region is deferred
  to just after the fused op (the snapshot then covers MORE work —
  strictly conservative, downstream waits over-wait, never under-wait).
  A pure single-lane compute schedule therefore fuses to ONE region.
  Within a region, ops on different lanes are data-independent **by
  soundness**: a cross-lane data dependency in a sound schedule always
  carries a record/wait pair, and that pair would have split the region —
  so executing the members in the chosen total order inside one kernel
  preserves every happens-before edge trivially.

* :class:`FusedRegionOp` lowers one region into a single ``pallas_call``
  specialized to the chosen total order: the kernel body re-applies the
  member ops' ``apply`` functions over in-kernel values, so intermediate
  buffers live in VMEM/registers instead of round-tripping HBM between
  separately-dispatched programs.  Only ops that declare
  ``DeviceOp.fusible()`` are ever fused (opt-in audit, core/operation.py);
  ``uses_pallas`` ops are excluded (no nested kernels).  When traced into
  the remainder program the fused op joins and advances EVERY member lane
  (``TraceContext.trace_fused``) — a conservative barrier, sound by
  construction.

* **Searchable tiling**: the kernel grid is ``(tiles,)`` over the region's
  declared row decomposition (``DeviceOp.fuse_tiling`` — per-buffer
  independence axes; lane placement already decided the region boundaries
  the grid specializes).  Tile counts are exposed as **decision nodes in
  the choice graph**: :func:`with_tile_menu` plants a
  :class:`FuseTileChoice` between Start and the first real ops, the
  solvers resolve it through the ordinary ``ChooseOp`` machinery (MCTS /
  DFS / hill-climb all search it with zero solver changes), the executed
  :class:`FuseTile` directive rides the schedule, and
  :class:`FusedExecutor` reads it back when lowering.
  ``bench/roofline.py::prune_tilings`` prunes counts that cannot help
  (per-tile traffic under the grid-overhead floor, or a working set that
  cannot fit VMEM).

* :class:`FusedExecutor` wraps a :class:`TraceExecutor` behind the same
  ``ScheduleRunner`` protocol the benchmarkers consume: ``prepare_n`` /
  ``prepare`` / ``run`` / ``compile`` lower through the fusion plan and
  delegate to the inner executor's program cache (plans are cached per
  schedule x tiles).  Kernels run in the Pallas interpreter off-TPU, like
  every kernel in ops/.

Integrity: the fused path is opt-in (``bench.py --fuse-winner``) and the
driver gates fused outputs through the PR-4 result-integrity machinery —
fused-program outputs must be allclose to the stepped program's, and the
schedule is re-verified — before stamping the ``perf.fused`` provenance
block.  Intra-region summation order is unchanged at ``tiles=1`` (the
kernel applies the same jax ops to the same full blocks — bit-identical in
practice); ``tiles>1`` re-associates across tile boundaries and is held to
the allclose gate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq, Tuple

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    ChoiceOp,
    CpuOp,
    DeviceOp,
    OpBase,
    register_kind,
    unbound,
)
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import EventRecord, SyncOp
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.runtime.executor import TraceExecutor, evolve_host_space


# -- tile decision nodes (the choice-graph surface) --------------------------

TILE_PREFIX = "fuse_tile.t"


@register_kind("fuse_tile")
class FuseTile(CpuOp):
    """The executed tile directive: a no-op host op named
    ``fuse_tile.t<N>`` whose only effect is to ride the schedule so the
    fusion backend (and the recorded-schedule corpus) can read the
    searched tile count back out.  A CpuOp so it costs nothing in the
    traced program and never lands inside a region."""

    def __init__(self, tiles: int):
        super().__init__(f"{TILE_PREFIX}{int(tiles)}")
        self._tiles = int(tiles)

    def tiles(self) -> int:
        return self._tiles

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(), "tiles": self._tiles}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "FuseTile":
        return cls(int(j["tiles"]))


class FuseTileChoice(ChoiceOp):
    """The tile-count menu as an ordinary ChoiceOp: the solvers resolve it
    through the same ChooseOp decision they use for kernel/engine menus, so
    tile/lane co-placement is searched *inside* the fused program by MCTS,
    DFS and hill-climb alike with zero solver changes."""

    def __init__(self, tile_counts: Seq[int], name: str = "fuse_tile"):
        super().__init__(name)
        self._tiles = [int(t) for t in tile_counts]
        if not self._tiles:
            raise ValueError("FuseTileChoice needs at least one tile count")

    def tile_counts(self) -> List[int]:
        return list(self._tiles)

    def choices(self) -> List[OpBase]:
        return [FuseTile(t) for t in self._tiles]


def with_tile_menu(graph: Graph, tile_counts: Seq[int]) -> Graph:
    """Clone ``graph`` with a :class:`FuseTileChoice` planted between Start
    and the original entry ops: the directive therefore always executes
    before any device op (it can never split a region mid-schedule), and
    every complete schedule carries exactly one tile directive."""
    g = graph.clone()
    choice = FuseTileChoice(tile_counts)
    entries = [s for s in list(g.succs(g.start())) if s != g.finish()]
    g.then(g.start(), choice)
    for e in entries:
        g.then(choice, e)
    if not entries:  # degenerate start->finish graph: keep choice reachable
        g.then(choice, g.finish())
    return g


def tiles_of(order) -> int:
    """The tile count a schedule's :class:`FuseTile` directive requests
    (1 when the schedule carries none)."""
    for op in order:
        name = op.name() if hasattr(op, "name") else ""
        if name.startswith(TILE_PREFIX):
            try:
                return max(1, int(name[len(TILE_PREFIX):]))
            except ValueError:
                continue
    return 1


# -- region model ------------------------------------------------------------


@dataclass
class Region:
    """One fusible region: the member ops in schedule order, plus the
    EventRecords deferred past the fused op (module docstring)."""

    members: List[BoundDeviceOp] = field(default_factory=list)
    deferred: List[OpBase] = field(default_factory=list)
    positions: List[int] = field(default_factory=list)

    def lanes(self) -> List:
        seen, out = set(), []
        for op in self.members:
            l = op.lane()
            if l.id not in seen:
                seen.add(l.id)
                out.append(l)
        return out

    def reads_external(self) -> List[str]:
        """Buffers the region reads from outside (first touch is a read)."""
        written: set = set()
        out: List[str] = []
        for op in self.members:
            for n in op.reads():
                if n not in written and n not in out:
                    out.append(n)
            written.update(op.writes())
        return out

    def writes(self) -> List[str]:
        out: List[str] = []
        for op in self.members:
            for n in op.writes():
                if n not in out:
                    out.append(n)
        return out


def _op_fusible(op: OpBase, host_space: set) -> bool:
    """Region membership test: an opt-in fusible BoundDeviceOp that emits no
    nested Pallas kernel, moves nothing between memory spaces, and touches
    no host-resident buffer at this point of the schedule."""
    if not isinstance(op, BoundDeviceOp):
        return False
    if op.uses_pallas() or not op.fusible():
        return False
    if getattr(unbound(op), "DST_SPACE", None) is not None:
        return False
    if not op.writes():
        return False
    if any(n in host_space for n in list(op.reads()) + list(op.writes())):
        return False
    return True


def partition_regions(ops: List[OpBase],
                      host_space: Optional[set] = None,
                      min_ops: int = 1) -> List[Tuple[str, Any]]:
    """Cut a complete schedule into segments: ``("region", Region)`` for
    each fusible run of at least ``min_ops`` member ops, ``("op", op)``
    for everything else, preserving schedule order (deferred EventRecords
    are re-emitted immediately after their region).  ``host_space`` is the
    set of buffer names fusion must treat as host-resident at schedule
    start — :meth:`FusedExecutor._host_space0` passes only the EXPLICITLY
    pinned-host names (see its docstring for why that is deliberately
    narrower than the executor's ``_initial_host_space`` probe) — evolved
    across transfer ops via the executor's shared
    :func:`~tenzing_tpu.runtime.executor.evolve_host_space` rule."""
    host = set(host_space) if host_space else set()
    segments: List[Tuple[str, Any]] = []
    cur: List[Tuple[int, OpBase, bool]] = []  # (pos, op, is_member)

    def flush() -> None:
        if not cur:
            return
        members = [(p, op) for p, op, m in cur if m]
        if len(members) >= max(1, min_ops):
            region = Region(
                members=[op for _, op in members],
                deferred=[op for _, op, m in cur if not m],
                positions=[p for p, _ in members],
            )
            segments.append(("region", region))
            for op in region.deferred:
                segments.append(("op", op))
        else:
            for _, op, _m in cur:  # replay in exact original order
                segments.append(("op", op))
        cur.clear()

    for pos, op in enumerate(ops):
        if isinstance(op, SyncOp):
            if isinstance(op, EventRecord) and any(m for _, _, m in cur):
                # outgoing snapshot: defer past the fused op (conservative)
                cur.append((pos, op, False))
                continue
            flush()
            segments.append(("op", op))
            continue
        if _op_fusible(op, host):
            cur.append((pos, op, True))
            continue
        flush()
        segments.append(("op", op))
        evolve_host_space(host, op)
    flush()
    return segments


# -- tiling ------------------------------------------------------------------


def region_axes(region: Region) -> Optional[Dict[str, Optional[int]]]:
    """The region's common row decomposition: per buffer, the independence
    axis every touching member agrees on (``None`` = full view).  Returns
    ``None`` — no tiling, single-tile kernel only — when any member is
    untileable, members disagree on a buffer's axis, or a written buffer
    would need a full (non-tiled) view (a full-block write from every grid
    step cannot be row-decomposed)."""
    axes: Dict[str, Optional[int]] = {}
    for op in region.members:
        t = op.fuse_tiling()
        if t is None:
            return None
        for n in set(op.reads()) | set(op.writes()):
            a = t.get(n)
            if n in axes and axes[n] != a:
                return None
            axes[n] = a
    for op in region.members:
        for n in op.writes():
            if axes.get(n) is None:
                return None
    return axes


def region_tile_counts(region: Region, shapes: Dict[str, Tuple[int, ...]],
                       max_tiles: int = 64) -> List[int]:
    """Structurally valid tile counts for a region: powers of two dividing
    every tiled buffer's extent along its declared axis.  ``[1]`` when the
    region admits no decomposition.  Roofline pruning
    (bench/roofline.prune_tilings) is applied by the caller — validity and
    profitability are different questions."""
    axes = region_axes(region)
    if axes is None:
        return [1]
    tiled = [(n, a) for n, a in axes.items() if a is not None]
    if not tiled:
        return [1]
    for n, a in tiled:
        if n not in shapes or a >= len(shapes[n]):
            return [1]
    out = [1]
    t = 2
    while t <= max_tiles:
        if all(shapes[n][a] % t == 0 and shapes[n][a] >= t
               for n, a in tiled):
            out.append(t)
        t *= 2
    return out


def region_bytes(region: Region, nbytes: Dict[str, int]) -> int:
    """The region's aggregate traffic (external reads + writes), for the
    roofline pruning join."""
    names = set(region.reads_external()) | set(region.writes())
    return sum(int(nbytes.get(n, 0)) for n in names)


# -- kernel lowering ---------------------------------------------------------


class _FusedCtx:
    """The minimal apply-context inside a fused kernel body: fusible ops
    are pure buffer->buffer functions, but the executor contract passes a
    ctx — give INDEX_TIE consumers a plain zero (tokens do not exist
    inside the kernel; ordering is the total order of the body itself)."""

    axis_names: Tuple[str, ...] = ()

    def __init__(self):
        import jax.numpy as jnp

        self.tok_index_zero = jnp.zeros((), jnp.int32)
        self.inflight: Dict[str, Any] = {}


def _region_call(members: List[BoundDeviceOp], in_names: List[str],
                 out_names: List[str], shapes: Dict[str, Tuple[int, ...]],
                 dtypes: Dict[str, Any],
                 axes: Optional[Dict[str, Optional[int]]],
                 tiles: int) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Build ``call(bufs) -> {written buffers}``: ONE ``pallas_call`` whose
    body applies the member ops in the chosen total order over in-kernel
    values.  ``tiles > 1`` blocks every buffer along its declared axis
    (grid ``(tiles,)``); full-view buffers are re-presented whole to every
    grid step."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from tenzing_tpu.ops.common import out_struct

    def block_shape(n: str) -> Tuple[int, ...]:
        shp = list(shapes[n])
        a = axes.get(n) if (axes and tiles > 1) else None
        if a is not None:
            shp[a] = shp[a] // tiles
        return tuple(shp)

    def index_map(n: str):
        rank = len(shapes[n])
        a = axes.get(n) if (axes and tiles > 1) else None
        if a is None:
            return lambda i, rank=rank: (0,) * rank
        return lambda i, a=a, rank=rank: tuple(
            i if k == a else 0 for k in range(rank))

    in_specs = [pl.BlockSpec(block_shape(n), index_map(n)) for n in in_names]
    out_specs = [pl.BlockSpec(block_shape(n), index_map(n))
                 for n in out_names]
    n_in = len(in_names)

    def kernel(*refs):
        ins, outs = refs[:n_in], refs[n_in:]
        vals = {n: r[...] for n, r in zip(in_names, ins)}
        ctx = _FusedCtx()
        for op in members:
            vals.update(op.apply(vals, ctx))
        for n, r in zip(out_names, outs):
            r[...] = jnp.asarray(vals[n]).astype(r.dtype)

    def call(bufs: Dict[str, Any]) -> Dict[str, Any]:
        operands = [bufs[n] for n in in_names]
        outs = pl.pallas_call(
            kernel,
            grid=(tiles,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=[out_struct(shapes[n], dtypes[n], *operands)
                       for n in out_names],
            interpret=jax.default_backend() != "tpu",
        )(*operands)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return dict(zip(out_names, outs))

    return call


class FusedRegionKernel(DeviceOp):
    """The unbound fused-region computation: reads the region's external
    inputs, writes its outputs, ``apply`` runs the single Pallas kernel."""

    KIND = "fused_region"

    def __init__(self, name: str, members: List[BoundDeviceOp],
                 in_names: List[str], out_names: List[str],
                 call: Callable, tiles: int):
        super().__init__(name)
        self._members = list(members)
        self._in = list(in_names)
        self._out = list(out_names)
        self._call = call
        self._tiles = int(tiles)

    def members(self) -> List[BoundDeviceOp]:
        return list(self._members)

    def tiles(self) -> int:
        return self._tiles

    def reads(self) -> List[str]:
        return list(self._in)

    def writes(self) -> List[str]:
        return list(self._out)

    def apply(self, bufs: Dict[str, Any], ctx) -> Dict[str, Any]:
        return self._call(bufs)

    def uses_pallas(self) -> bool:
        return True

    def desc(self) -> str:
        return (f"{self.name()}({'+'.join(m.name() for m in self._members)})")

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(),
                "members": [m.name() for m in self._members],
                "tiles": self._tiles}


class FusedRegionOp(BoundDeviceOp):
    """The bound fused region: owns EVERY member lane (its trace joins and
    advances all of them — ``TraceContext.trace_fused`` — so replacing the
    members can only add happens-before edges, never drop one)."""

    def __init__(self, kernel: FusedRegionKernel, lanes: List):
        super().__init__(kernel, lanes[0])
        self._all_lanes = list(lanes)

    def lanes(self) -> List:
        return list(self._all_lanes)

    def trace(self, tc) -> None:
        tc.trace_fused(self)

    def to_json(self) -> Dict[str, Any]:
        j = self.unbound().to_json()
        j["lane"] = self.lane().id
        j["lanes"] = [l.id for l in self._all_lanes]
        return j


# -- the fusion plan + executor ----------------------------------------------


@dataclass
class RegionInfo:
    """Provenance for one lowered region (the ``perf.fused`` block)."""

    n_ops: int
    members: List[str]
    lanes: List[int]
    tiles: int
    valid_tiles: List[int]
    pruned_tiles: List[int]

    def to_json(self) -> Dict[str, Any]:
        return {"n_ops": self.n_ops, "members": list(self.members),
                "lanes": list(self.lanes), "tiles": self.tiles,
                "valid_tiles": list(self.valid_tiles),
                "pruned_tiles": list(self.pruned_tiles)}


@dataclass
class FusionPlan:
    """What :meth:`FusedExecutor.plan` decided for one schedule: the fused
    order (regions replaced by :class:`FusedRegionOp`) plus provenance."""

    fused_order: Sequence
    regions: List[RegionInfo]
    tiles_requested: int
    n_ops_total: int
    n_ops_fused: int

    @property
    def tile_menu(self) -> List[int]:
        """Tile counts worth searching: valid-and-unpruned for at least
        one region (always contains 1)."""
        menu = {1}
        for r in self.regions:
            menu.update(r.pruned_tiles)
        return sorted(menu)

    def to_json(self) -> Dict[str, Any]:
        return {
            "regions": len(self.regions),
            "region_sizes": [r.n_ops for r in self.regions],
            "tiles_requested": self.tiles_requested,
            "tile_menu": self.tile_menu,
            "n_ops_total": self.n_ops_total,
            "n_ops_fused": self.n_ops_fused,
            "region_detail": [r.to_json() for r in self.regions],
        }


class FusedExecutor:
    """The opt-in fusion path behind the ``ScheduleRunner`` protocol: every
    ``prepare/prepare_n/run/compile`` lowers the schedule through the
    fusion plan and delegates to the wrapped :class:`TraceExecutor` (whose
    program cache keys on the FUSED sequence's JSON, so fused and stepped
    programs of the same schedule coexist).

    ``tiles=None`` reads the schedule's :class:`FuseTile` directive (the
    searched decision); an explicit ``tiles`` overrides it (the driver's
    tile-menu sweep).  A requested count invalid for some region falls
    back to that region's best valid divisor of the request — regions
    independently keep the largest decomposition the request admits.

    ``min_tile_bytes``/``vmem_bytes`` parameterize the roofline pruning
    (bench/roofline.prune_tilings); tests shrink them to exercise the
    menu on toy buffers."""

    def __init__(self, inner: TraceExecutor, tiles: Optional[int] = None,
                 min_ops: int = 1,
                 min_tile_bytes: Optional[int] = None,
                 vmem_bytes: Optional[int] = None):
        self.inner = inner
        self.tiles = tiles
        self.min_ops = min_ops
        self.min_tile_bytes = min_tile_bytes
        self.vmem_bytes = vmem_bytes
        self._plans: Dict[Tuple, FusionPlan] = {}

    # -- delegated surface --------------------------------------------------
    @property
    def platform(self):
        return self.inner.platform

    @property
    def init_bufs(self):
        return self.inner.init_bufs

    @property
    def compile_count(self) -> int:
        return self.inner.compile_count

    @property
    def compile_secs(self) -> float:
        return self.inner.compile_secs

    # -- planning -----------------------------------------------------------
    def _host_space0(self) -> set:
        """Buffers whose arrays are EXPLICITLY pinned to host memory (the
        ``place_host_buffers`` staging buffers).  Deliberately narrower
        than the executor's ``_initial_host_space`` substring probe: the
        CPU backend reports ``unpinned_host`` for EVERY array (it is host
        memory), which would classify the whole buffer dict host-resident
        and leave nothing fusible — but only ``pinned_host`` tensors carry
        the no-arithmetic restriction fusion must respect."""
        names = set()
        for k, v in self.inner.init_bufs.items():
            mk = getattr(getattr(v, "sharding", None), "memory_kind", None)
            if mk is not None and str(mk) == "pinned_host":
                names.add(k)
        return names

    def _shapes_dtypes(self):
        shapes = {k: tuple(getattr(v, "shape", ()))
                  for k, v in self.inner.init_bufs.items()}
        dtypes = {k: getattr(v, "dtype", None)
                  for k, v in self.inner.init_bufs.items()}
        nbytes = {k: int(getattr(v, "nbytes", 0))
                  for k, v in self.inner.init_bufs.items()}
        return shapes, dtypes, nbytes

    def _pruned_tiles(self, region: Region, valid: List[int],
                      nbytes: Dict[str, int]) -> List[int]:
        from tenzing_tpu.bench import roofline

        cost = roofline.Cost(flops=0.0,
                             hbm_bytes=float(region_bytes(region, nbytes)))
        # full-view buffers (declared axis None) are re-presented whole to
        # every grid step: their bytes do not shrink with the tile count
        axes = region_axes(region) or {}
        touched = set(region.reads_external()) | set(region.writes())
        full = float(sum(int(nbytes.get(n, 0)) for n in touched
                         if axes.get(n) is None))
        kw: Dict[str, Any] = {"full_bytes": full}
        if self.min_tile_bytes is not None:
            kw["min_tile_bytes"] = self.min_tile_bytes
        if self.vmem_bytes is not None:
            kw["vmem_bytes"] = self.vmem_bytes
        return roofline.prune_tilings(cost, valid, **kw)

    def plan(self, order: Sequence) -> FusionPlan:
        """The fusion plan for ``order`` (cached per schedule x tiles)."""
        from tenzing_tpu.core.serdes import sequence_to_json_str

        tiles_req = self.tiles if self.tiles is not None else tiles_of(order)
        key = (sequence_to_json_str(order), int(tiles_req), self.min_ops,
               self.min_tile_bytes, self.vmem_bytes)
        hit = self._plans.get(key)
        if hit is not None:
            return hit
        ops = order.vector()
        shapes, dtypes, nbytes = self._shapes_dtypes()
        segments = partition_regions(
            ops, host_space=self._host_space0(), min_ops=self.min_ops)
        fused_ops: List[OpBase] = []
        infos: List[RegionInfo] = []
        n_fused = 0
        with get_tracer().span("fused.plan", n_ops=len(ops),
                               tiles=int(tiles_req)):
            for kind, seg in segments:
                if kind == "op":
                    fused_ops.append(seg)
                    continue
                region: Region = seg
                valid = region_tile_counts(region, shapes)
                pruned = self._pruned_tiles(region, valid, nbytes)
                t = _best_divisor(int(tiles_req), pruned)
                in_names = region.reads_external()
                out_names = region.writes()
                axes = region_axes(region)
                call = _region_call(region.members, in_names, out_names,
                                    shapes, dtypes, axes, t)
                idx = len(infos)
                kernel = FusedRegionKernel(
                    f"fused{idx}.t{t}", region.members, in_names, out_names,
                    call, t)
                fused_ops.append(FusedRegionOp(kernel, region.lanes()))
                infos.append(RegionInfo(
                    n_ops=len(region.members),
                    members=[m.name() for m in region.members],
                    lanes=[l.id for l in region.lanes()],
                    tiles=t, valid_tiles=valid, pruned_tiles=pruned))
                n_fused += len(region.members)
        plan = FusionPlan(fused_order=Sequence(fused_ops), regions=infos,
                          tiles_requested=int(tiles_req),
                          n_ops_total=len(ops), n_ops_fused=n_fused)
        get_metrics().counter("fused.plans").inc()
        get_metrics().counter("fused.regions").inc(len(infos))
        self._plans[key] = plan
        return plan

    def fused_order(self, order: Sequence) -> Sequence:
        return self.plan(order).fused_order

    # -- ScheduleRunner protocol --------------------------------------------
    def precompile(self, order: Sequence) -> bool:
        """AOT-compile the FUSED program for ``order`` (the prefetch
        pipeline's background-worker entry): lowering first keeps the
        cache key the fused sequence's JSON, so the foreground
        ``prepare_n`` of the same schedule hits."""
        return self.inner.precompile(self.fused_order(order))

    def prepare(self, order: Sequence):
        return self.inner.prepare(self.fused_order(order))

    def prepare_n(self, order: Sequence):
        return self.inner.prepare_n(self.fused_order(order))

    def run(self, order: Sequence) -> Dict[str, Any]:
        return self.inner.run(self.fused_order(order))

    def compile(self, order: Sequence):
        return self.inner.compile(self.fused_order(order))


def _best_divisor(want: int, menu: List[int]) -> int:
    """The largest menu entry dividing ``want`` (1 is always a divisor and
    always on the menu) — a region keeps the biggest decomposition the
    requested tile count admits."""
    best = 1
    for t in menu:
        if t <= want and want % t == 0 and t > best:
            best = t
    return best


def fused_summary(plan: FusionPlan) -> str:
    """One human line for stderr provenance."""
    return (f"{len(plan.regions)} region(s) over {plan.n_ops_fused}/"
            f"{plan.n_ops_total} ops, sizes "
            f"{[r.n_ops for r in plan.regions]}, tiles "
            f"{[r.tiles for r in plan.regions]}")


__all__ = [
    "FuseTile", "FuseTileChoice", "with_tile_menu", "tiles_of",
    "Region", "partition_regions", "region_axes", "region_tile_counts",
    "region_bytes", "FusedRegionKernel", "FusedRegionOp",
    "RegionInfo", "FusionPlan", "FusedExecutor", "fused_summary",
]
