"""Host-side coordination: the control plane of the search.

Parity target: the reference's second use of MPI (SURVEY.md §5 "Distributed
communication backend"): schedule broadcast (sequence.cpp:88-125 ``mpi_bcast``),
stop-flag broadcast (mcts.hpp:148-151, dfs.hpp:66-69), and benchmark barriers and
max-over-hosts reductions (benchmarker.cpp:43-60,101,145).

TPU-native realization: ``jax.process_index``/``process_count`` identify hosts;
cross-host exchange rides a length-padded uint8 array through
``multihost_utils.broadcast_one_to_all`` and max-reductions through
``process_allgather`` — the data plane (ICI/DCN collectives inside schedules)
lives in the ops, not here.  On a single host every operation degenerates to the
identity, so the whole search stack runs un-distributed (the reference behaves
identically under an MPI world of size 1).
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from tenzing_tpu.obs.tracer import get_tracer


class ControlPlane:
    """Single-host control plane (world size 1) — the default."""

    def rank(self) -> int:
        return 0

    def size(self) -> int:
        return 1

    def barrier(self) -> None:
        return None

    def bcast_json(self, obj: Any) -> Any:
        """Broadcast a JSON-serializable object from rank 0 (reference
        mpi_bcast's length+bytes protocol, sequence.cpp:88-125)."""
        return obj

    def allreduce_max(self, x: float) -> float:
        """Max over hosts (reference MPI_Allreduce MAX, benchmarker.cpp:101,145)."""
        return x

    def agree_fault(self, code: int) -> int:
        """Rank-coherent failure agreement — THE primitive
        ``fault.resilient.ResilientBenchmarker`` brackets every measurement
        attempt with: each rank contributes its local fault code
        (``fault.errors.FaultClass.CODES``, 0 = healthy, ordered by
        severity) and every rank receives the worst code seen anywhere, so
        a failure on one rank becomes a failure on all ranks at the same
        attempt boundary instead of a deadlock in the next collective.
        Expressed over :meth:`allreduce_max` so both realizations (identity
        on one host, ``process_allgather`` under jax.distributed) agree."""
        return int(self.allreduce_max(float(code)))


class JaxControlPlane(ControlPlane):
    """Multi-host control plane over jax.distributed (requires
    jax.distributed.initialize to have run)."""

    def __init__(self):
        import jax

        self._jax = jax
        # tag all telemetry with this host's rank: multi-host trace bundles
        # merge into one Perfetto timeline with one process row per rank
        get_tracer().set_rank(self.rank())

    def rank(self) -> int:
        return self._jax.process_index()

    def size(self) -> int:
        return self._jax.process_count()

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("tenzing_tpu_barrier")

    def bcast_json(self, obj: Any) -> Any:
        from jax.experimental import multihost_utils

        payload = json.dumps(obj).encode() if self.rank() == 0 else b""
        n = np.array([len(payload)], dtype=np.int64)
        n = multihost_utils.broadcast_one_to_all(n)
        buf = np.zeros(int(n[0]), dtype=np.uint8)
        if self.rank() == 0:
            buf[:] = np.frombuffer(payload, dtype=np.uint8)
        buf = multihost_utils.broadcast_one_to_all(buf)
        return json.loads(bytes(buf).decode())

    def allreduce_max(self, x: float) -> float:
        from jax.experimental import multihost_utils

        xs = multihost_utils.process_allgather(np.array([x]))
        return float(np.max(xs))


class FileControlPlane(ControlPlane):
    """Same-host *process-fleet* control plane over a shared directory — the
    coordination substrate of the distributed search fleet
    (``search/fleet.py``): N worker processes plus one measurement owner,
    none of which share a jax.distributed world.

    Two primitives, both deliberately **non-blocking**:

    * ``publish``/``gather`` — monotonic snapshot exchange.  Each rank
      atomically replaces its own ``<tag>.r<rank>.json`` (generation-stamped);
      ``gather`` reads whatever snapshots currently exist.  This is how
      incumbents and visit statistics "allreduce" across the fleet: every
      rank eventually sees every other rank's latest snapshot, and the
      reduction (min cost, union of visited keys) happens in the reader.
    * ``claim`` — an atomic winner-takes-all registry (``O_EXCL`` create,
      the lease protocol's claim step without the lease): the first rank to
      claim a key owns it, rivals get False.  The fleet claims canonical
      schedule digests before measuring, which keeps subtrees *dynamically*
      disjoint — a neighbor another worker already paid for is skipped.

    Lockstep collectives (``barrier``/``bcast_json``/``allreduce_max``)
    keep the single-host identity semantics inherited from
    :class:`ControlPlane`: a fleet member can be SIGKILLed and its subtree
    re-adopted mid-run (serve/lease.py reclaim), so any blocking rendezvous
    would deadlock the survivors.  Device-side measurement coherence is the
    *owner's* concern — workers never call into jax at all."""

    def __init__(self, root: str, rank: int, size: int):
        import os

        self.root = root
        self._rank = int(rank)
        self._size = int(size)
        self._gen = 0
        os.makedirs(root, exist_ok=True)

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    # -- snapshot exchange ---------------------------------------------------
    def publish(self, tag: str, obj: Any) -> None:
        """Atomically replace this rank's snapshot under ``tag``."""
        import os

        from tenzing_tpu.utils.atomic import atomic_dump_json

        self._gen += 1
        atomic_dump_json(
            os.path.join(self.root, f"{tag}.r{self._rank}.json"),
            {"gen": self._gen, "rank": self._rank, "data": obj})

    def gather(self, tag: str, include_self: bool = True) -> dict:
        """``{rank: data}`` over every currently-published snapshot of
        ``tag``.  Torn/missing files are skipped — a snapshot is an
        optimization hint, never a correctness gate."""
        import os

        from tenzing_tpu.utils.atomic import read_json

        out = {}
        prefix, suffix = f"{tag}.r", ".json"
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(prefix) and name.endswith(suffix)):
                continue
            try:
                rank = int(name[len(prefix):-len(suffix)])
            except ValueError:
                continue
            if not include_self and rank == self._rank:
                continue
            try:
                out[rank] = read_json(
                    os.path.join(self.root, name))["data"]
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out

    # -- winner-takes-all claims --------------------------------------------
    def claim(self, tag: str, key: str) -> bool:
        """True iff this rank is the FIRST in the fleet to claim ``key``
        under ``tag`` (atomic ``O_EXCL`` create).  On registry I/O trouble
        the claim is granted: a double measurement wastes budget, a
        wrongly-skipped candidate loses coverage."""
        import os

        d = os.path.join(self.root, f"claims-{tag}")
        try:
            os.makedirs(d, exist_ok=True)
            fd = os.open(os.path.join(d, key),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return True
        try:
            os.write(fd, str(self._rank).encode())
        except OSError:
            pass
        finally:
            os.close(fd)
        return True

    def claim_count(self, tag: str) -> int:
        """How many keys have been claimed under ``tag`` fleet-wide."""
        import os

        try:
            return len(os.listdir(os.path.join(self.root, f"claims-{tag}")))
        except OSError:
            return 0


_DEFAULT: ControlPlane = ControlPlane()


def default_control_plane() -> ControlPlane:
    """The process-global control plane: multi-host iff jax reports >1 process."""
    global _DEFAULT
    try:
        import jax

        if jax.process_count() > 1 and not isinstance(_DEFAULT, JaxControlPlane):
            _DEFAULT = JaxControlPlane()
    except Exception:
        pass
    return _DEFAULT
