"""Host-side coordination: the control plane of the search.

Parity target: the reference's second use of MPI (SURVEY.md §5 "Distributed
communication backend"): schedule broadcast (sequence.cpp:88-125 ``mpi_bcast``),
stop-flag broadcast (mcts.hpp:148-151, dfs.hpp:66-69), and benchmark barriers and
max-over-hosts reductions (benchmarker.cpp:43-60,101,145).

TPU-native realization: ``jax.process_index``/``process_count`` identify hosts;
cross-host exchange rides a length-padded uint8 array through
``multihost_utils.broadcast_one_to_all`` and max-reductions through
``process_allgather`` — the data plane (ICI/DCN collectives inside schedules)
lives in the ops, not here.  On a single host every operation degenerates to the
identity, so the whole search stack runs un-distributed (the reference behaves
identically under an MPI world of size 1).
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from tenzing_tpu.obs.tracer import get_tracer


class ControlPlane:
    """Single-host control plane (world size 1) — the default."""

    def rank(self) -> int:
        return 0

    def size(self) -> int:
        return 1

    def barrier(self) -> None:
        return None

    def bcast_json(self, obj: Any) -> Any:
        """Broadcast a JSON-serializable object from rank 0 (reference
        mpi_bcast's length+bytes protocol, sequence.cpp:88-125)."""
        return obj

    def allreduce_max(self, x: float) -> float:
        """Max over hosts (reference MPI_Allreduce MAX, benchmarker.cpp:101,145)."""
        return x

    def agree_fault(self, code: int) -> int:
        """Rank-coherent failure agreement — THE primitive
        ``fault.resilient.ResilientBenchmarker`` brackets every measurement
        attempt with: each rank contributes its local fault code
        (``fault.errors.FaultClass.CODES``, 0 = healthy, ordered by
        severity) and every rank receives the worst code seen anywhere, so
        a failure on one rank becomes a failure on all ranks at the same
        attempt boundary instead of a deadlock in the next collective.
        Expressed over :meth:`allreduce_max` so both realizations (identity
        on one host, ``process_allgather`` under jax.distributed) agree."""
        return int(self.allreduce_max(float(code)))


class JaxControlPlane(ControlPlane):
    """Multi-host control plane over jax.distributed (requires
    jax.distributed.initialize to have run)."""

    def __init__(self):
        import jax

        self._jax = jax
        # tag all telemetry with this host's rank: multi-host trace bundles
        # merge into one Perfetto timeline with one process row per rank
        get_tracer().set_rank(self.rank())

    def rank(self) -> int:
        return self._jax.process_index()

    def size(self) -> int:
        return self._jax.process_count()

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("tenzing_tpu_barrier")

    def bcast_json(self, obj: Any) -> Any:
        from jax.experimental import multihost_utils

        payload = json.dumps(obj).encode() if self.rank() == 0 else b""
        n = np.array([len(payload)], dtype=np.int64)
        n = multihost_utils.broadcast_one_to_all(n)
        buf = np.zeros(int(n[0]), dtype=np.uint8)
        if self.rank() == 0:
            buf[:] = np.frombuffer(payload, dtype=np.uint8)
        buf = multihost_utils.broadcast_one_to_all(buf)
        return json.loads(bytes(buf).decode())

    def allreduce_max(self, x: float) -> float:
        from jax.experimental import multihost_utils

        xs = multihost_utils.process_allgather(np.array([x]))
        return float(np.max(xs))


_DEFAULT: ControlPlane = ControlPlane()


def default_control_plane() -> ControlPlane:
    """The process-global control plane: multi-host iff jax reports >1 process."""
    global _DEFAULT
    try:
        import jax

        if jax.process_count() > 1 and not isinstance(_DEFAULT, JaxControlPlane):
            _DEFAULT = JaxControlPlane()
    except Exception:
        pass
    return _DEFAULT
