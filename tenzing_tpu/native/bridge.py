"""ctypes bridge to the native search core (native/ at the repo root).

Lowering: a Python Graph whose vertices are all Start/Finish/CpuOp/DeviceOp/
BoundDeviceOp (i.e. compound/choice ops already expanded) maps to the native
description — ops numbered in vertex-insertion order, kinds, the edge list in
insertion order (order matters: decision enumeration must match the Python
layer exactly).  Schedules cross the boundary as (tag, a, b) int32 triples
(native/include/tznative/core.hpp Tag).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    BoundOp,
    CpuOp,
    DeviceOp,
    Finish,
    OpBase,
    Start,
)
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.state import AssignLane, Decision, ExecuteOp, State
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, LaneSync, SyncOp, WaitEvent

# kinds/tags — keep in sync with native/include/tznative/core.hpp
KIND_HOST, KIND_DEVICE, KIND_START, KIND_FINISH = 0, 1, 2, 3
TAG_EXEC, TAG_RECORD, TAG_WAIT, TAG_SYNC_EVENT, TAG_SYNC_LANE, TAG_ASSIGN = range(6)

TZ_ERROR = -1000000000

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_SO_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_tznative.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


class NotLowerable(Exception):
    """The graph/sequence contains ops the native core cannot represent."""


class NativeError(RuntimeError):
    pass


def _mode() -> str:
    return os.environ.get("TENZING_TPU_NATIVE", "auto").lower()


def _sources_mtime() -> float:
    newest = 0.0
    for root, _dirs, files in os.walk(_NATIVE_DIR):
        for f in files:
            if f.endswith((".cpp", ".hpp")) or f == "Makefile":
                newest = max(newest, os.path.getmtime(os.path.join(root, f)))
    return newest


def _build() -> None:
    """Run make under an exclusive file lock: concurrent processes (multi-host
    control plane, parallel pytest) must not race writes to the same .so."""
    import fcntl

    lock_path = os.path.join(os.path.dirname(_SO_PATH), ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            # a racer may have finished the build while we waited for the lock
            if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= _sources_mtime():
                return
            p = subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                capture_output=True,
                text=True,
                timeout=300,
            )
            if p.returncode != 0:
                raise NativeError(f"native build failed:\n{p.stdout}\n{p.stderr}")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _mode() in ("0", "off", "false"):
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_failed and _mode() != "1":
            return None
        try:
            if not os.path.exists(_SO_PATH) or os.path.getmtime(_SO_PATH) < _sources_mtime():
                _build()
            lib = ctypes.CDLL(_SO_PATH)
            lib.tz_abi_version.restype = ctypes.c_int32
            if lib.tz_abi_version() != 2:
                raise NativeError("native ABI version mismatch; run make -C native clean")
            lib.tz_last_error.restype = ctypes.c_char_p
            lib.tz_graph_create.restype = ctypes.c_void_p
            lib.tz_graph_create.argtypes = [
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.tz_graph_destroy.argtypes = [ctypes.c_void_p]
            lib.tz_decisions.restype = ctypes.c_int64
            lib.tz_decisions.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
            ]
            lib.tz_rollout.restype = ctypes.c_int64
            lib.tz_rollout.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
            ]
            lib.tz_enum_run.restype = ctypes.c_int64
            lib.tz_enum_run.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.tz_enum_fetch.restype = ctypes.c_int64
            lib.tz_enum_fetch.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
            ]
            _lib = lib
            return _lib
        except Exception:
            _lib_failed = True
            if _mode() == "1":
                raise
            return None


def native_available() -> bool:
    return _load() is not None


# -- lowering -----------------------------------------------------------------


class LoweredGraph:
    """A Python Graph lowered to a native handle + the vertex table for mapping
    results back to op objects."""

    def __init__(self, graph: Graph):
        lib = _load()
        if lib is None:
            raise NativeError("native library unavailable")
        self._lib = lib
        self.vertices: List[OpBase] = graph.vertices()
        self.index: Dict[Tuple, int] = {}
        kinds = []
        for i, v in enumerate(self.vertices):
            kinds.append(_kind_of(v))
            self.index[v.eq_key()] = i
        edges: List[int] = []
        n_edges = 0
        for v in self.vertices:
            for s in graph.succs(v):
                edges += [self.index[v.eq_key()], self.index[s.eq_key()]]
                n_edges += 1
        kinds_arr = (ctypes.c_int32 * len(kinds))(*kinds)
        edges_arr = (ctypes.c_int32 * max(1, len(edges)))(*edges)
        self.n = len(self.vertices)
        self.handle = lib.tz_graph_create(self.n, kinds_arr, n_edges, edges_arr)
        if not self.handle:
            raise NativeError(lib.tz_last_error().decode())

    def __del__(self):
        h = getattr(self, "handle", None)
        if h:
            self._lib.tz_graph_destroy(h)
            self.handle = None

    # -- python -> native --------------------------------------------------
    def bindings_of(self, graph: Graph):
        """Per-op lane bindings from a (possibly re-bound clone of the) graph
        with the same structure."""
        b = [-1] * self.n
        for v in graph.vertices():
            i = self.index.get(v.eq_key())
            if i is None:
                raise NotLowerable(f"graph vertex {v!r} absent from lowered structure")
            if isinstance(v, BoundDeviceOp):
                b[i] = v.lane().id
        return (ctypes.c_int32 * self.n)(*b)

    def lower_sequence(self, seq: Sequence):
        items: List[int] = []
        for op in seq:
            if isinstance(op, EventRecord):
                items += [TAG_RECORD, op.lane().id, op.event().id]
            elif isinstance(op, WaitEvent):
                items += [TAG_WAIT, op.lane().id, op.event().id]
            elif isinstance(op, EventSync):
                items += [TAG_SYNC_EVENT, op.event().id, -1]
            elif isinstance(op, LaneSync):
                items += [TAG_SYNC_LANE, op.lane().id, -1]
            elif isinstance(op, SyncOp):
                raise NotLowerable(f"sync op {op!r} has no native representation")
            else:
                i = self.index.get(op.eq_key())
                if i is None:
                    raise NotLowerable(f"sequence op {op!r} not a graph vertex")
                lane = op.lane().id if isinstance(op, BoundDeviceOp) else -1
                items += [TAG_EXEC, i, lane]
        n = len(items) // 3
        return n, (ctypes.c_int32 * max(1, len(items)))(*items)

    # -- native -> python --------------------------------------------------
    def item_to_op(self, tag: int, a: int, b: int) -> OpBase:
        if tag == TAG_EXEC:
            v = self.vertices[a]
            if b >= 0:
                if isinstance(v, BoundDeviceOp):
                    return v if v.lane().id == b else v.with_lane(Lane(b))
                assert isinstance(v, DeviceOp), v
                return v.bind(Lane(b))
            return v
        if tag == TAG_RECORD:
            return EventRecord(Lane(a), Event(b))
        if tag == TAG_WAIT:
            return WaitEvent(Lane(a), Event(b))
        if tag == TAG_SYNC_EVENT:
            return EventSync(Event(a))
        if tag == TAG_SYNC_LANE:
            return LaneSync(Lane(a))
        raise NativeError(f"unexpected item tag {tag}")

    def items_to_sequence(self, flat, n_items: int) -> Sequence:
        return Sequence(
            self.item_to_op(flat[3 * i], flat[3 * i + 1], flat[3 * i + 2])
            for i in range(n_items)
        )

    def decision_of(self, tag: int, a: int, b: int, graph: Graph) -> Decision:
        if tag == TAG_ASSIGN:
            v = graph.vertex(self.vertices[a])
            assert isinstance(v, DeviceOp) and not isinstance(v, BoundDeviceOp), v
            return AssignLane(v, Lane(b))
        if tag == TAG_EXEC:
            # the graph's stored vertex carries the current binding
            v = graph.vertex(self.vertices[a])
            assert isinstance(v, BoundOp), v
            return ExecuteOp(v)
        return ExecuteOp(self.item_to_op(tag, a, b))


# Structural cache: MCTS/DFS lower thousands of States whose graphs are
# re-bound clones of a handful of structures (eq_key is binding-insensitive),
# so the native handle + vertex table are reusable; only bindings_of /
# lower_sequence vary per call.
_LG_CACHE: Dict[Tuple, "LoweredGraph"] = {}
_LG_CACHE_LOCK = threading.Lock()
_LG_CACHE_MAX = 128


def _kind_of(v: OpBase) -> int:
    if isinstance(v, Start):
        return KIND_START
    if isinstance(v, Finish):
        return KIND_FINISH
    if isinstance(v, (DeviceOp, BoundDeviceOp)):
        return KIND_DEVICE
    if isinstance(v, CpuOp):
        return KIND_HOST
    raise NotLowerable(f"vertex {v!r} (expand compound/choice ops first)")


def lowered_graph_for(graph: Graph) -> "LoweredGraph":
    """The cached lowering of this graph's structure (binding-insensitive)."""
    verts = graph.vertices()
    idx = {v.eq_key(): i for i, v in enumerate(verts)}
    key = (
        tuple(v.eq_key() for v in verts),
        tuple(_kind_of(v) for v in verts),
        tuple(tuple(idx[s.eq_key()] for s in graph.succs(v)) for v in verts),
    )
    with _LG_CACHE_LOCK:
        lg = _LG_CACHE.get(key)
        if lg is None:
            if len(_LG_CACHE) >= _LG_CACHE_MAX:
                _LG_CACHE.clear()
            lg = LoweredGraph(graph)
            _LG_CACHE[key] = lg
        return lg


def _lanes_are_dense(platform) -> bool:
    """The native core enumerates lane indices 0..n-1; bail out (to the Python
    path) for platforms whose lane ids aren't exactly that."""
    return [l.id for l in platform.lanes] == list(range(len(platform.lanes)))


def _lower_state(state: State):
    lg = lowered_graph_for(state.graph)
    bindings = lg.bindings_of(state.graph)
    seq_len, seq_arr = lg.lower_sequence(state.sequence)
    return lg, bindings, seq_len, seq_arr


# -- solver entry points ------------------------------------------------------


def try_decisions(state: State, platform) -> Optional[List[Decision]]:
    """Native get_decisions, or None when native is unavailable/not applicable."""
    if _load() is None or not _lanes_are_dense(platform):
        return None
    try:
        lg, bindings, seq_len, seq_arr = _lower_state(state)
    except NotLowerable:
        return None
    cap = (lg.n * max(1, len(platform.lanes)) + 16) * 3
    out = (ctypes.c_int32 * cap)()
    n = lg._lib.tz_decisions(
        lg.handle, len(platform.lanes), bindings, seq_len, seq_arr, out, cap
    )
    if n == TZ_ERROR:
        raise NativeError(lg._lib.tz_last_error().decode())
    if n < 0:  # pragma: no cover - cap is sized generously
        out = (ctypes.c_int32 * (-n))()
        n = lg._lib.tz_decisions(
            lg.handle, len(platform.lanes), bindings, seq_len, seq_arr, out, -n
        )
        if n < 0:
            raise NativeError(lg._lib.tz_last_error().decode())
    return [
        lg.decision_of(out[3 * i], out[3 * i + 1], out[3 * i + 2], state.graph)
        for i in range(n // 3)
    ]


def try_rollout(state: State, platform, seed: int) -> Optional[Sequence]:
    """Native random playout to a terminal sequence, or None."""
    if _load() is None or not _lanes_are_dense(platform):
        return None
    try:
        lg, bindings, seq_len, seq_arr = _lower_state(state)
    except NotLowerable:
        return None
    cap = (lg.n * 8 + 64) * 3
    out = (ctypes.c_int32 * cap)()
    n = lg._lib.tz_rollout(
        lg.handle, len(platform.lanes), bindings, seq_len, seq_arr,
        seed & 0xFFFFFFFFFFFFFFFF, out, cap,
    )
    if n == TZ_ERROR:
        raise NativeError(lg._lib.tz_last_error().decode())
    if n < 0:
        out = (ctypes.c_int32 * (-n))()
        n = lg._lib.tz_rollout(
            lg.handle, len(platform.lanes), bindings, seq_len, seq_arr,
            seed & 0xFFFFFFFFFFFFFFFF, out, -n,
        )
        if n < 0:
            raise NativeError(lg._lib.tz_last_error().decode())
    return lg.items_to_sequence(out, n // 3)


def try_enumerate(
    graph: Graph, platform, max_seqs: int, dedup_terminals: bool = True
) -> Optional[List[State]]:
    """Native exhaustive enumeration -> States with lane-bound graphs, or None."""
    if _load() is None or not _lanes_are_dense(platform):
        return None
    try:
        lg = lowered_graph_for(graph)
    except NotLowerable:
        return None
    n_lanes = len(platform.lanes)
    n_seqs = ctypes.c_int32(0)
    # two-phase: run once (honoring caller-pinned lane bindings), then fetch
    # into an exactly-sized buffer
    total = lg._lib.tz_enum_run(
        lg.handle, n_lanes, lg.bindings_of(graph), max_seqs,
        1 if dedup_terminals else 0, ctypes.byref(n_seqs),
    )
    if total == TZ_ERROR:
        raise NativeError(lg._lib.tz_last_error().decode())
    out = (ctypes.c_int32 * max(1, total))()
    n = lg._lib.tz_enum_fetch(out, total)
    assert n == total, (n, total)
    states: List[State] = []
    w = 0
    for _ in range(n_seqs.value):
        n_items = out[w]
        w += 1
        ops = [
            lg.item_to_op(out[w + 3 * i], out[w + 3 * i + 1], out[w + 3 * i + 2])
            for i in range(n_items)
        ]
        w += 3 * n_items
        seq = Sequence(ops)
        assignment = {
            op.unbound(): op.lane() for op in ops if isinstance(op, BoundDeviceOp)
        }
        bound_graph = graph.apply_lane_assignment(assignment) if assignment else graph
        states.append(State(bound_graph, seq))
    return states
