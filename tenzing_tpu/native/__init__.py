"""Native (C++) search core bindings.

The reference implements its scheduler entirely in C++ (SURVEY.md §2); here the
host-side search hot path — frontier/decision enumeration, sync inference,
equivalence-dedup'd DFS, random rollouts — has a C++17 implementation
(``native/`` at the repo root) loaded via ctypes.  The Python implementations in
``tenzing_tpu.core`` remain the reference semantics; solvers call
``bridge.try_*`` helpers which return ``None`` when the native library is
unavailable or the graph is not lowerable, falling back to Python.

Set ``TENZING_TPU_NATIVE=0`` to disable, ``=1`` to require (build errors raise).
"""

from tenzing_tpu.native.bridge import (  # noqa: F401
    NotLowerable,
    native_available,
    try_decisions,
    try_enumerate,
    try_rollout,
)
