"""Shared bounded-retry helper: exponential backoff with jitter.

THE one retry implementation for the whole runtime (ISSUE 3 satellite —
``bench.py`` previously carried two ad-hoc one-shot retry loops): callers
describe *what* to retry (:class:`BackoffPolicy`, a ``retry_on`` predicate)
and :func:`retry_call` handles the loop, the sleeps, and the telemetry —
every retry lands as a ``fault.retry`` trace event (attempt count, error
class, delay) and a ``fault.retries`` counter bump, so flaky-tunnel spells
are visible in the bundle instead of silently stretching the wall clock.

Jitter is a +/- fraction of the exponential delay, drawn from the caller's
RNG (seedable — the chaos tests replay exact schedules).  Sleeping is
injectable for the same reason.
"""

from __future__ import annotations

import random as _random
import time
from dataclasses import dataclass
from typing import Callable, Optional, TypeVar

from tenzing_tpu.fault.errors import FaultClass, classify_error
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer

T = TypeVar("T")


@dataclass(frozen=True)
class BackoffPolicy:
    """``retries`` extra attempts after the first; attempt ``k`` (0-based
    retry index) sleeps ``min(base_secs * factor**k, max_secs)`` +/- a
    ``jitter`` fraction of itself."""

    retries: int = 3
    base_secs: float = 0.5
    factor: float = 2.0
    max_secs: float = 30.0
    jitter: float = 0.25

    def delay(self, retry_index: int, rng: Optional[_random.Random] = None) -> float:
        d = min(self.base_secs * (self.factor ** retry_index), self.max_secs)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


def _default_retry_on(exc: BaseException) -> bool:
    """Retry exactly the transient class — deterministic failures re-raise
    immediately (retrying re-pays a failing compile for the same verdict)
    and device-lost escalates to the caller."""
    return classify_error(exc) == FaultClass.TRANSIENT


def retry_call(
    fn: Callable[[], T],
    *,
    policy: Optional[BackoffPolicy] = None,
    retry_on: Optional[Callable[[BaseException], bool]] = None,
    where: str = "",
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[_random.Random] = None,
) -> T:
    """Call ``fn()`` with bounded classified retries; return its result.

    ``retry_on(exc) -> bool`` gates each retry (default: transient-class
    only).  ``on_retry(exc, attempt, delay)`` runs before each sleep — the
    hook callers use for recovery work between attempts (e.g.
    ``jax.extend.backend.clear_backends()`` before re-probing a failed
    backend init).  The final failure re-raises the last exception."""
    policy = policy if policy is not None else BackoffPolicy()
    retry_on = retry_on if retry_on is not None else _default_retry_on
    rng = rng if rng is not None else _random.Random()
    attempts = policy.retries + 1
    for attempt in range(attempts):
        try:
            return fn()
        except BaseException as e:
            if attempt == attempts - 1 or not retry_on(e):
                raise
            delay = policy.delay(attempt, rng)
            get_metrics().counter("fault.retries").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event(
                    "fault.retry", where=where, attempt=attempt + 1,
                    error=type(e).__name__, error_class=classify_error(e),
                    message=str(e)[:200], delay_secs=round(delay, 4),
                )
            if on_retry is not None:
                on_retry(e, attempt, delay)
            if delay > 0.0:
                sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
