"""Hostile-filesystem chaos acceptance: the serve plane's disaster
drill, runnable as ``python -m tenzing_tpu.fault.fschaos``.

Two phases, one JSON verdict line, exit 0 iff every invariant held:

**Phase 1 — fleet chaos runs.**  Per run (``--runs``, each with its own
derived seed): enqueue real cold work items, start the REAL supervisor
(serve/supervisor.py) as a subprocess in ``--drain-exit`` mode, and give
its members a hostile filesystem — the seeded fsinject spec
(fault/fsinject.py) rides into each member through an ``env``-wrapped
``--member-argv``, so daemons and their drain children see injected
EIO/ENOSPC/torn renames/stale reads/skewed lease clocks while the
supervisor's own control plane (and this harness's audits) observe the
truthful disk.  Mid-drain the harness SIGKILLs one member's whole
process group.  The per-run audit is the acceptance contract:

* **zero acknowledged-record loss** — every enqueued fingerprint's
  record is present in the final store, and ``serve fsck`` over it
  reports no errors;
* **exactly-once drain effect** — the supervisor's status-history audit
  (serve/fleet.py ``audit_completions``) shows no double-runs even with
  member lease clocks skewed/coarsened under it (epoch fencing,
  serve/lease.py);
* **no work left behind** — supervisor rc 0, reason ``drained``, empty
  queue, no poison quarantine;
* **service answers throughout** — a probe thread resolves the enqueued
  fingerprints against the store for the whole run; a degraded shed
  (StoreReadonlyError) is an acceptable answer, an unexpected exception
  is a violation, and by the end every fingerprint must resolve exact.

**Phase 2 — ``store_unwritable`` fire/resolve drill.**  Deterministic
and in-process, through the real code paths: an injected ENOSPC latches
the read-only degradation (serve/store.py ``guarded_store_write``); a
chmod-0o500 store directory keeps the daemon's probe failing for real,
so the drain daemon visibly pauses claims (status ``paused`` with the
latch doc); the alert evaluator fires ``store_unwritable``; restoring
the mode lets the daemon's next probe clear the latch, resume, and the
alert resolves.  This is the drill CI's hostile-fs smoke asserts on
(docs/robustness.md "Disaster recovery").
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import tenzing_tpu

REPO = os.path.abspath(os.path.join(
    os.path.dirname(tenzing_tpu.__file__), ".."))

# the default hostile mount, parameterized by the run's seed: transient
# EIO bursts (retried), a rare single-fire ENOSPC (degrade + recover —
# the deterministic latch drill is phase 2; at a high rate the drain
# child's own checkpoint writes fail identically on every retry and the
# item is *correctly* poisoned, which is not the invariant under test),
# raise-mode torn renames (param=1 — the harness supplies the hard
# deaths itself via SIGKILL), NFS-style stale re-reads, and skewed +
# coarsened lease clocks (the epoch-fencing gauntlet)
DEFAULT_FAULTS = ("eio:0.08:{s}:3,enospc:0.02:{s}:1,torn_rename:0.03:{s}:1,"
                  "stale_read:0.3:{s}:4,mtime_skew:0.35:{s}:2.5,"
                  "mtime_coarse:0.6:{s}:2")


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _wait_for(pred: Callable[[], Any], timeout_s: float, what: str):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        v = pred()
        if v:
            return v
        time.sleep(0.1)
    raise RuntimeError(f"timed out waiting for {what}")


def _requests(n: int) -> List[Any]:
    """``n`` distinct smoke work items.  All attn — the one smoke
    workload whose drain needs no ``pinned_host`` memory space (absent
    on CPU-only backends; spmv/moe/halo all stage host buffers there) —
    with distinct lane counts for distinct exact fingerprints (the
    mesh's lane count is part of the fingerprint)."""
    from tenzing_tpu.bench.driver import DriverRequest

    return [DriverRequest(workload="attn", smoke=True, lanes=2 * (i + 1),
                          mcts_iters=4, climb_budget=4, search_iters=2,
                          iters=4, measure_timeout=300.0)
            for i in range(n)]


def _member_argv(queue_dir: str, store: str, fault_spec: str) -> List[str]:
    """The chaos member: the stock drain daemon argv, env-wrapped so the
    member process (and every drain child it spawns) inherits the
    hostile filesystem via ``TENZING_FSINJECT`` — without the supervisor
    itself ever writing through the inject seam."""
    return ["env", f"TENZING_FSINJECT={fault_spec}", "JAX_PLATFORMS=cpu",
            "XLA_FLAGS=--xla_force_host_platform_device_count=8",
            sys.executable, "-m", "tenzing_tpu.serve.daemon",
            "--queue", queue_dir, "--store", store, "--owner", "{owner}",
            # TTL sized ABOVE the worst-case injected timestamp error
            # (skew 2.5s + coarse 2s from DEFAULT_FAULTS): the run's
            # lesson is that a lease TTL below the filesystem's clock
            # error LIVELOCKS the fleet — rivals reclaim live leases
            # forever, every reclaim aborts a real drain attempt, and
            # epoch fencing keeps it correct-but-starving.  A SIGKILLed
            # member's item is still reclaimed within ~8s.
            "--idle-exit", "1.0", "--poll", "0.2", "--lease-ttl", "8",
            "--heartbeat", "0.3", "--topk", "3", "--item-timeout", "300",
            "--retries", "3", "--max-failures", "6"]


def _sup_cmd(queue_dir: str, store: str, member_argv: List[str],
             daemons: int) -> List[str]:
    return [sys.executable, "-m", "tenzing_tpu.serve.supervisor",
            "--queue", queue_dir, "--store", store,
            "--min-daemons", str(daemons), "--max-daemons", str(daemons),
            "--tick", "0.2", "--heartbeat", "0.3",
            "--compact-interval", "0", "--gc-interval", "0",
            "--scale-hold-ticks", "1000000",
            "--member-lease-ttl", "8", "--member-heartbeat", "0.3",
            "--member-poll", "0.2", "--backoff-base", "0.3",
            "--breaker-max-restarts", "6",
            "--drain-exit",
            "--member-argv", json.dumps(member_argv)]


class _Probe(threading.Thread):
    """Service-continuity probe: resolve every enqueued fingerprint
    against the store, clean-env, for the whole run.  A degraded shed
    counts as an answer; any other exception is a violation."""

    def __init__(self, store: str, reqs: List[Any]):
        super().__init__(daemon=True)
        self.store = store
        self.reqs = reqs
        self.stop = threading.Event()
        self.probes = 0
        self.degraded = 0
        self.tiers: Dict[str, str] = {}
        self.violations: List[str] = []

    def _pass(self) -> None:
        from tenzing_tpu.fault.errors import StoreReadonlyError
        from tenzing_tpu.serve.fingerprint import fingerprint_of
        from tenzing_tpu.serve.service import ScheduleService

        svc = ScheduleService(self.store, queue_dir=None, verify=True)
        for req in self.reqs:
            self.probes += 1
            exact = fingerprint_of(req).exact_digest
            try:
                res = svc.query(req)
                self.tiers[exact] = res.tier
            except StoreReadonlyError:
                self.degraded += 1  # an honest degraded answer
            except Exception as e:  # noqa: BLE001 — the audit ledger
                self.violations.append(f"probe {exact[:12]}: "
                                       f"{type(e).__name__}: {e}")

    def run(self) -> None:
        while not self.stop.is_set():
            try:
                self._pass()
            except Exception as e:  # noqa: BLE001
                self.violations.append(f"probe pass: {type(e).__name__}: {e}")
            self.stop.wait(0.5)
        self._pass()  # the post-drain pass: everything must be exact now


def _fault_evidence(queue_dir: str) -> Dict[str, int]:
    """Best-effort ``fault.fsinjected.*`` totals from the members'
    metric-snapshot rings — proof the run exercised the fault paths."""
    totals: Dict[str, int] = {}

    def walk(obj: Any) -> None:
        if isinstance(obj, dict):
            for k, v in obj.items():
                if isinstance(k, str) and k.startswith("fault.fsinjected.") \
                        and isinstance(v, (int, float)):
                    totals[k] = totals.get(k, 0) + int(v)
                else:
                    walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)

    for path in glob.glob(os.path.join(queue_dir, "metrics-*.json")):
        doc = _read_json(path)
        if doc:
            walk(doc)
    return totals


def _chaos_run(workdir: str, run: int, seed: int, items: int,
               faults: str, daemons: int, timeout_s: float,
               log: Callable[[str], None]) -> Dict[str, Any]:
    from tenzing_tpu.serve import dr
    from tenzing_tpu.serve.fingerprint import fingerprint_of
    from tenzing_tpu.serve.fleet import audit_completions
    from tenzing_tpu.serve.store import WorkQueue, open_store

    rdir = os.path.join(workdir, f"run-{run}")
    queue_dir = os.path.join(rdir, "q")
    store = os.path.join(rdir, "store")
    os.makedirs(store, exist_ok=True)
    spec = faults.format(s=seed)
    doc: Dict[str, Any] = {"run": run, "seed": seed, "faults": spec,
                           "violations": []}
    bad = doc["violations"].append

    q = WorkQueue(queue_dir)
    reqs = _requests(items)
    exacts = []
    for req in reqs:
        fp = fingerprint_of(req)
        exacts.append(fp.exact_digest)
        q.enqueue(fp, req.to_json(), reason="cold")

    probe = _Probe(store, reqs)
    probe.start()
    env = dict(os.environ)
    env.pop("TENZING_FSINJECT", None)  # the controller stays truthful
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        _sup_cmd(queue_dir, store, _member_argv(queue_dir, store, spec),
                 daemons),
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    killed = False
    try:
        # SIGKILL one member's whole session (daemon AND drain child)
        # once it has claimed work — the exactly-once half of the drill
        try:
            member = _wait_for(
                lambda: _read_json(
                    os.path.join(queue_dir, "status-fleet-0.json")),
                60.0, "the first member's status doc")
            _wait_for(
                lambda: glob.glob(os.path.join(queue_dir, "lease-*.json")),
                60.0, "a claimed lease")
            try:
                os.killpg(int(member["pid"]), signal.SIGKILL)
                killed = True
                log(f"run {run}: SIGKILLed member pg {member['pid']}")
            except (ProcessLookupError, PermissionError):
                killed = True  # already dead: an injected torn publish won
        except RuntimeError as e:
            bad(f"chaos setup: {e}")
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        bad(f"supervisor did not drain within {timeout_s:.0f}s")
    finally:
        probe.stop.set()
        probe.join(timeout=30.0)

    doc["member_sigkilled"] = killed
    doc["supervisor_rc"] = proc.returncode
    summary: Dict[str, Any] = {}
    try:
        summary = json.loads(out.splitlines()[-1])
    except (IndexError, ValueError):
        bad("supervisor printed no summary line")
    doc["summary"] = {k: summary.get(k) for k in
                      ("reason", "counters", "double_runs",
                       "audit_complete", "queue_after")}

    if proc.returncode != 0:
        bad(f"supervisor rc {proc.returncode}: {err[-800:]}")
    if summary.get("reason") != "drained":
        bad(f"supervisor reason {summary.get('reason')!r}, want 'drained'")
    if summary.get("double_runs"):
        bad(f"double runs: {summary['double_runs']}")
    if len(q) != 0:
        bad(f"{len(q)} items left in the queue")
    poison = glob.glob(os.path.join(queue_dir, "poison-*.json"))
    if poison:
        bad(f"poisoned items: {[os.path.basename(p) for p in poison]}")

    # the harness's own exactly-once audit, over every fleet owner that
    # ever wrote a status doc (restarted incarnations share the owner)
    owners = sorted(
        os.path.basename(p)[len("status-"):-len(".json")]
        for p in glob.glob(os.path.join(queue_dir, "status-fleet-*.json")))
    audit = audit_completions(queue_dir, owners)
    doc["audit"] = audit
    if audit["double_runs"]:
        bad(f"status-history double runs: {audit['double_runs']}")

    # zero acknowledged-record loss: every fingerprint answers from the
    # final store, and a deep fsck walk finds no damage
    st = open_store(store)
    missing = [e for e in exacts if st.best(e) is None]
    if missing:
        bad(f"records lost for {[e[:12] for e in missing]}")
    fsck = dr.fsck_store(store, check_backups=False)
    doc["fsck"] = {"rc": fsck["rc"], "errors": fsck["errors"],
                   "warnings": fsck.get("warnings", [])}
    if fsck["errors"]:
        bad(f"fsck errors: {fsck['errors']}")

    # service answered throughout, and everything resolves exact now
    doc["probe"] = {"probes": probe.probes, "degraded": probe.degraded,
                    "violations": probe.violations}
    doc["violations"].extend(probe.violations)
    not_exact = [e for e in exacts if probe.tiers.get(e) != "exact"]
    if not_exact:
        bad(f"final probe tier not exact for {[e[:12] for e in not_exact]}")

    doc["fault_evidence"] = _fault_evidence(queue_dir)
    doc["ok"] = not doc["violations"]
    log(f"run {run}: {'ok' if doc['ok'] else 'FAILED'} "
        f"(probes {probe.probes}, degraded {probe.degraded}, "
        f"injected {doc['fault_evidence']})")
    return doc


class _ScopedEnospc:
    """A full disk under ONE directory tree: the seam backend the drill
    installs so every store write (including the recovery probe) keeps
    failing ENOSPC while daemon status/queue writes land normally.
    chmod can't play this role — the harness may run as root, and root
    ignores permission bits."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root) + os.sep
        self.fires = 0

    def check(self, op: str, path: str) -> None:
        import errno

        if op == "write" and os.path.abspath(path).startswith(self.root):
            self.fires += 1
            raise OSError(errno.ENOSPC,
                          f"injected enospc (fschaos drill {path})")

    def maybe_stale_json(self, path: str):
        return None

    def observe_mtime(self, path: str, mtime: float) -> float:
        return mtime


def _unwritable_drill(workdir: str, seed: int,
                      log: Callable[[str], None]) -> Dict[str, Any]:
    """Phase 2 (module docstring): ENOSPC latch -> daemon pauses ->
    ``store_unwritable`` fires -> probe write lands -> daemon resumes ->
    the alert resolves.  Every step through the production code path."""
    from tenzing_tpu.fault import fsinject
    from tenzing_tpu.obs.alerts import AlertBook, evaluate
    from tenzing_tpu.serve.daemon import DaemonOpts, DrainDaemon
    from tenzing_tpu.serve.store import (clear_store_unwritable,
                                         guarded_store_write,
                                         store_readonly)
    from tenzing_tpu.utils.atomic import atomic_dump_json
    from tenzing_tpu.utils.atomic import set_io_backend as _atomic_set_backend

    ddir = os.path.join(workdir, "drill")
    queue_dir = os.path.join(ddir, "q")
    store = os.path.join(ddir, "store")
    os.makedirs(queue_dir, exist_ok=True)
    os.makedirs(store, exist_ok=True)
    doc: Dict[str, Any] = {"violations": []}
    bad = doc["violations"].append
    status_path = os.path.join(queue_dir, "status-drill.json")
    book = AlertBook(os.path.join(ddir, "alerts.json"),
                     resolve_hold_secs=0.0)

    def alert_entry() -> Optional[Dict[str, Any]]:
        entries = book.apply(evaluate([store], [queue_dir]))["alerts"]
        for key, e in entries.items():
            if key.startswith("store_unwritable:"):
                return e
        return None

    # first, prove the seeded spec grammar drives the same latch: one
    # bounded ENOSPC burst through the real fsinject backend
    backend = fsinject.install(f"enospc:1.0:{seed}:1")
    try:
        try:
            guarded_store_write(
                store, lambda: atomic_dump_json(
                    os.path.join(store, "drill.json"), {"n": 1}))
            bad("injected ENOSPC did not surface through the guard")
        except OSError:
            pass  # the expected degradation
    finally:
        fsinject.uninstall()
    doc["injected"] = dict(backend.injected)
    if store_readonly(store) is None:
        bad("store did not latch read-only on ENOSPC")

    # then hold the disk full for the store tree only, so the daemon's
    # recovery probe keeps failing while its status writes land
    scoped = _ScopedEnospc(store)
    _atomic_set_backend(scoped)
    d = DrainDaemon(
        DaemonOpts(queue_dir=queue_dir, store_path=store, owner="drill",
                   in_process=True, handle_signals=False, poll_secs=0.1,
                   heartbeat_secs=0.2, lease_ttl_secs=2.0,
                   status_path=status_path),
        runner=lambda path, payload, timeout: {},
        log=None)
    t = threading.Thread(target=d.run, daemon=True)
    t.start()
    try:
        _wait_for(
            lambda: (_read_json(status_path) or {}).get("state") == "paused"
            and (_read_json(status_path) or {}).get("store_readonly"),
            20.0, "the daemon's paused status doc")
        e = alert_entry()
        doc["fired"] = bool(e and e.get("state") == "firing")
        if not doc["fired"]:
            bad("store_unwritable did not fire while latched")
        else:
            log("drill: store_unwritable firing (daemon paused)")

        _atomic_set_backend(None)  # "the operator freed space"
        _wait_for(
            lambda: not (_read_json(status_path) or {}).get("store_readonly"),
            20.0, "the probe write to clear the latch")
        e = alert_entry()
        doc["resolved"] = bool(e and e.get("state") == "resolved")
        if not doc["resolved"]:
            bad("store_unwritable did not resolve after the probe landed")
        else:
            log("drill: store_unwritable resolved (claims resumed)")
    except RuntimeError as err:
        bad(str(err))
    finally:
        _atomic_set_backend(None)
        clear_store_unwritable(store)
        d.stop()
        t.join(timeout=20.0)
    doc["probe_write_denials"] = scoped.fires
    doc["ok"] = not doc["violations"]
    return doc


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.fault.fschaos",
        description="hostile-filesystem chaos acceptance for the serve "
                    "plane (module docstring)")
    ap.add_argument("--workdir", required=True,
                    help="scratch root for queues/stores/alert books")
    ap.add_argument("--runs", type=int, default=3,
                    help="fleet chaos runs (each under a derived seed)")
    ap.add_argument("--items", type=int, default=2,
                    help="cold work items per run")
    ap.add_argument("--daemons", type=int, default=2,
                    help="fleet members per run")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--faults", default=DEFAULT_FAULTS,
                    help="fsinject spec template; {s} is the run seed")
    ap.add_argument("--run-timeout", type=float, default=540.0,
                    help="per-run supervisor drain budget (seconds)")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke: one run, one item")
    ap.add_argument("--skip-fleet", action="store_true",
                    help="phase 2 drill only (no subprocess fleet)")
    args = ap.parse_args(argv)
    if args.quick:
        args.runs, args.items = 1, 1

    log = lambda m: sys.stderr.write(m + "\n")  # noqa: E731
    os.makedirs(args.workdir, exist_ok=True)
    runs: List[Dict[str, Any]] = []
    if not args.skip_fleet:
        for r in range(args.runs):
            runs.append(_chaos_run(args.workdir, r, args.seed + r,
                                   args.items, args.faults, args.daemons,
                                   args.run_timeout, log))
    drill = _unwritable_drill(args.workdir, args.seed, log)

    verdict = {
        "kind": "fschaos_verdict",
        "seed": args.seed,
        "runs": runs,
        "drill": drill,
        "invariants": {
            "no_record_loss": all(r["ok"] for r in runs),
            "exactly_once": all(not r.get("audit", {}).get("double_runs")
                                for r in runs),
            "service_answered": all(not r["probe"]["violations"]
                                    for r in runs),
            "unwritable_fired_and_resolved": drill["ok"],
        },
        "ok": all(r["ok"] for r in runs) and drill["ok"],
    }
    sys.stdout.write(json.dumps(verdict) + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
