"""Persistent per-schedule quarantine for deterministic failures.

A schedule that fails *deterministically* (compile error, liveness beyond
device memory, shape violation — fault/errors.py) will fail the same way on
every future attempt; re-measuring it burns a compile-and-crash cycle per
encounter.  The quarantine records such candidates by their telemetry
schedule id (``obs.tracer.short_digest`` of the serialized sequence — the
same id every span/event carries, so quarantine entries correlate with the
trace) and answers future queries instantly with
:class:`~tenzing_tpu.fault.errors.QuarantinedScheduleError`.

File format (docs/robustness.md): one JSON document
``{"version": 1, "entries": {<schedule-id>: {"error": <exception type>,
"error_class": ..., "message": ..., "n_ops": ...}}}`` rewritten atomically
(tmp + rename) on every addition — additions are rare (one per broken
candidate, ever) so the rewrite is cheap, and a crash mid-write leaves the
previous complete file in place.  A missing or unreadable file is an empty
quarantine (quarantine is an optimization, never a correctness gate), but
an unreadable file is *reported* — silently dropping it would re-measure
every quarantined candidate.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from tenzing_tpu.bench.benchmarker import schedule_id
from tenzing_tpu.utils.atomic import atomic_dump_json
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer

QUARANTINE_VERSION = 1


class Quarantine:
    """In-memory set of broken schedule ids, optionally file-backed.

    ``path=None`` keeps the quarantine process-local (tests, callers that
    manage persistence themselves); with a path, the constructor loads any
    existing file and every :meth:`add` persists atomically."""

    def __init__(self, path: Optional[str] = None, log=None):
        self.path = path
        self._log = log
        self.entries: Dict[str, dict] = {}
        if path is not None and os.path.exists(path):
            self._load(path)

    def _load(self, path: str) -> None:
        try:
            with open(path) as f:
                doc = json.load(f)
            if doc.get("version") != QUARANTINE_VERSION:
                raise ValueError(
                    f"quarantine version {doc.get('version')!r} != "
                    f"{QUARANTINE_VERSION}")
            self.entries = dict(doc["entries"])
        except Exception as e:
            self.entries = {}
            if self._log is not None:
                self._log(f"quarantine: ignoring unreadable {path}: "
                          f"{type(e).__name__}: {e}")

    def __len__(self) -> int:
        return len(self.entries)

    def key(self, order) -> str:
        return schedule_id(order)

    def check(self, order) -> Optional[dict]:
        """The quarantine record for ``order``, or None when clean."""
        return self.entries.get(self.key(order))

    def add(self, order, exc: BaseException, error_class: str) -> str:
        """Quarantine ``order`` (idempotent) and persist; returns the id."""
        sid = self.key(order)
        if sid not in self.entries:
            self.entries[sid] = {
                "error": type(exc).__name__,
                "error_class": error_class,
                "message": str(exc)[:500],
                "n_ops": len(order) if hasattr(order, "__len__") else None,
            }
            get_metrics().counter("fault.quarantined").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event("fault.quarantine", schedule=sid,
                         error=type(exc).__name__, error_class=error_class)
            self._persist()
        return sid

    def _persist(self) -> None:
        if self.path is None:
            return
        atomic_dump_json(
            self.path,
            {"version": QUARANTINE_VERSION, "entries": self.entries},
            prefix=".quarantine.")
