"""Fault tolerance for the search runtime (ISSUE 3, docs/robustness.md).

The paper's core loop — empirically benchmark thousands of candidate
schedules on real hardware across all ranks — is exactly the loop most
exposed to real-machine flakiness.  This package makes a multi-hour search
survive a flaky tunnel, a hung compile, a broken candidate, a dead chip,
and a Ctrl-C without losing its corpus:

* :mod:`~tenzing_tpu.fault.errors` — the failure taxonomy (transient /
  deterministic / device-lost) and :func:`classify_error`.
* :mod:`~tenzing_tpu.fault.backoff` — the shared bounded-retry helper
  (exponential backoff + jitter, ``fault.retry`` telemetry).
* :mod:`~tenzing_tpu.fault.quarantine` — persistent per-schedule quarantine
  of deterministically-broken candidates.
* :mod:`~tenzing_tpu.fault.resilient` — :class:`ResilientBenchmarker`:
  watchdog timeout, classified retries, rank-coherent failure agreement,
  graceful degradation to a fallback benchmarker.
* :mod:`~tenzing_tpu.fault.checkpoint` — atomic checkpoint/resume: the
  measurement journal + solver cursors (``bench.py --checkpoint --resume``).
* :mod:`~tenzing_tpu.fault.inject` — seeded chaos:
  :class:`FaultInjectingBenchmarker` (``bench.py --inject-faults``).
"""

from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
from tenzing_tpu.fault.checkpoint import (
    CheckpointError,
    JournalingBenchmarker,
    SearchCheckpoint,
    atomic_write_json,
    read_checked_json,
)
from tenzing_tpu.fault.errors import (
    DeterministicScheduleError,
    DeviceLostError,
    FaultClass,
    MeasurementTimeout,
    QuarantinedScheduleError,
    StoreLockTimeout,
    TransientError,
    UnsoundScheduleError,
    classify_error,
    fault_code,
)
from tenzing_tpu.fault.inject import (
    FaultInjectingBenchmarker,
    InjectSpec,
    InjectedDeterministicError,
    InjectedTransientError,
    corrupt_schedule,
    parse_inject_specs,
)
from tenzing_tpu.fault.quarantine import Quarantine
from tenzing_tpu.fault.resilient import ResilientBenchmarker

__all__ = [
    "BackoffPolicy",
    "CheckpointError",
    "DeterministicScheduleError",
    "DeviceLostError",
    "FaultClass",
    "FaultInjectingBenchmarker",
    "InjectSpec",
    "InjectedDeterministicError",
    "InjectedTransientError",
    "JournalingBenchmarker",
    "MeasurementTimeout",
    "Quarantine",
    "QuarantinedScheduleError",
    "ResilientBenchmarker",
    "SearchCheckpoint",
    "StoreLockTimeout",
    "TransientError",
    "UnsoundScheduleError",
    "atomic_write_json",
    "classify_error",
    "corrupt_schedule",
    "fault_code",
    "parse_inject_specs",
    "read_checked_json",
    "retry_call",
]
