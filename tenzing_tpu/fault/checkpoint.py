"""Checkpoint/resume for long searches.

A multi-hour empirical search must survive a kill — SIGINT, a SLURM wall
clock, a crashed tunnel — without losing its corpus.  The checkpoint layout
(one directory, ``bench.py --checkpoint DIR``):

* ``measurements.jsonl`` — the **measurement journal**: one JSON line per
  completed device measurement (serialized ops, the BenchOpts fidelity key,
  the full BenchResult, provenance tag), appended and flushed *as each
  measurement lands* — crash-safe by construction; a torn tail line (killed
  mid-write) is detected and skipped on load.  Paired-batch results
  (``benchmark_batch_times`` — the hill-climb's accept primitive) journal
  into the same file as ``{"batch": ...}`` lines keyed by (batch-member
  schedule ids, decorrelation seed, fidelity key), so a resumed paired
  climb replays its accept batches device-free too.
* ``state.json`` — solver cursors + run config, written **atomically**
  (tmp + rename) as a versioned, sha256-digest-checked envelope
  (:func:`atomic_write_json`); a corrupt or version-mismatched file raises
  :class:`CheckpointError` instead of silently resuming from garbage.
* ``quarantine.json`` — fault/quarantine.py's persistent broken-candidate
  set (kept in the same directory so one ``--checkpoint DIR`` carries all
  cross-restart state).

**Resume model** (docs/robustness.md): the searches are deterministic given
their seeds and their measurement answers.  ``--resume`` therefore restores
the journal into the run's equivalence-keyed ``CachingBenchmarker`` and
re-executes the search from the top: every already-measured schedule is a
cache hit (zero device time, bit-identical BenchResult — floats round-trip
exactly through JSON), so the MCTS tree, the DFS frontier walk and the
hill-climb chain reconstruct *exactly* up to the kill point and continue
live from there.  No already-measured schedule touches the device again,
and the final best matches an uninterrupted run (tests/test_chaos_search.py
asserts both).  The solver cursors in ``state.json`` are consistency
metadata: resume sanity-checks the workload config digest against them.

Degraded-mode rows are journaled with their provenance but **not**
restored into the cache: on a healthy resumed device they should be
re-measured, not replayed as if they were measurements.  (Model-answered
queries never reach the journal at all — the learned screen wraps
*outside* the caching/journaling stack, bench.py — but the restore filter
skips any non-``measured`` provenance, so a journal written by a future
layer that does tag ``model`` rows degrades safely too.)
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.utils.atomic import atomic_dump_json  # noqa: F401 — re-export:
# the raw helper was born here and grew callers (fault/quarantine.py,
# historical imports); the one definition now lives in utils/atomic.py

CHECKPOINT_VERSION = 1

# the drain daemon wires its lease's fencing token to the checkpoint
# journal through the environment (`<lease-path>:<epoch>`): the drain —
# in-process or a --exec-item subprocess — then refuses to append
# journal lines once a rival claim supersedes the lease (serve/lease.py
# "Epoch fencing"), so a zombie holder cannot interleave stale rows into
# the successor's journal
FENCE_ENV = "TENZING_FENCE"


def _fence_from_env():
    """The env-wired fence check (see :data:`FENCE_ENV`); None when no
    fence is declared.  Parsed lazily per checkpoint object — the daemon
    sets the variable around each drained item."""
    spec = os.environ.get(FENCE_ENV)
    if not spec or ":" not in spec:
        return None
    path, _, epoch_s = spec.rpartition(":")
    try:
        epoch = int(epoch_s)
    except ValueError:
        return None

    def check() -> None:
        from tenzing_tpu.serve.lease import check_epoch

        check_epoch(path, epoch)

    return check

# journal provenance tags: only MEASURED rows restore into the cache
PROVENANCE_MEASURED = "measured"
PROVENANCE_DEGRADED = "degraded"
PROVENANCE_MODEL = "model"


class CheckpointError(RuntimeError):
    """A checkpoint file exists but cannot be trusted (bad digest/version)."""


def _digest(payload_text: str) -> str:
    return hashlib.sha256(payload_text.encode()).hexdigest()


def atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    """Write ``payload`` as a versioned digest-checked envelope via
    :func:`~tenzing_tpu.utils.atomic.atomic_dump_json`."""
    text = json.dumps(payload, sort_keys=True)
    atomic_dump_json(path, {"version": CHECKPOINT_VERSION,
                            "digest": _digest(text), "payload": payload},
                     prefix=".ckpt.")


def read_checked_json(path: str) -> Dict[str, Any]:
    """Read an :func:`atomic_write_json` envelope, verifying version and
    digest; raises :class:`CheckpointError` on any mismatch."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable checkpoint {path}: {e}") from e
    if doc.get("version") != CHECKPOINT_VERSION:
        raise CheckpointError(
            f"checkpoint {path}: version {doc.get('version')!r} != "
            f"{CHECKPOINT_VERSION}")
    payload = doc.get("payload")
    text = json.dumps(payload, sort_keys=True)
    if _digest(text) != doc.get("digest"):
        raise CheckpointError(f"checkpoint {path}: digest mismatch "
                              "(truncated or corrupted)")
    return payload


def _opts_key(opts: Optional[BenchOpts]) -> Optional[List[float]]:
    if opts is None:
        return None
    return [opts.n_iters, opts.max_retries, opts.target_secs]


def _opts_from_key(key) -> Optional[BenchOpts]:
    if key is None:
        return None
    return BenchOpts(n_iters=int(key[0]), max_retries=int(key[1]),
                     target_secs=float(key[2]))


def _result_from_json(j: Dict[str, Any]) -> BenchResult:
    return BenchResult(
        pct01=j["pct01"], pct10=j["pct10"], pct50=j["pct50"],
        pct90=j["pct90"], pct99=j["pct99"], stddev=j["stddev"],
        times=j.get("times"), fetch_overhead=j.get("fetch_overhead"),
    )


class SearchCheckpoint:
    """One checkpoint directory (see module docstring).  ``fence`` is an
    optional zero-arg callable raising
    :class:`~tenzing_tpu.fault.errors.FencedWriteError` when this
    writer's lease has been superseded — checked before every journal
    append and state snapshot; defaults to the daemon's env-wired token
    (:data:`FENCE_ENV`), None when unfenced."""

    def __init__(self, directory: str, fence=None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._journal_f = None
        self._state: Dict[str, Any] = {}
        self._fence = fence if fence is not None else _fence_from_env()

    def _check_fence(self) -> None:
        if self._fence is not None:
            self._fence()

    # -- paths -------------------------------------------------------------
    @property
    def state_path(self) -> str:
        return os.path.join(self.dir, "state.json")

    @property
    def journal_path(self) -> str:
        return os.path.join(self.dir, "measurements.jsonl")

    @property
    def quarantine_path(self) -> str:
        return os.path.join(self.dir, "quarantine.json")

    # -- measurement journal ------------------------------------------------
    def record(self, order, opts: Optional[BenchOpts], res: BenchResult,
               provenance: str = PROVENANCE_MEASURED) -> None:
        """Append one measurement, flushed immediately (crash-safe)."""
        from tenzing_tpu.core.serdes import sequence_to_json

        line = json.dumps({
            "opts": _opts_key(opts),
            "prov": provenance,
            "result": res.to_json(),
            "ops": sequence_to_json(order),
        }, sort_keys=True)
        self._check_fence()
        if self._journal_f is None:
            self._journal_f = open(self.journal_path, "a")
        self._journal_f.write(line + "\n")
        self._journal_f.flush()
        os.fsync(self._journal_f.fileno())
        get_metrics().counter("fault.checkpoint.journaled").inc()

    def record_batch(self, ids: List[str], opts: Optional[BenchOpts],
                     seed: int, times: List[List[float]],
                     groups=None) -> None:
        """Append one ``benchmark_batch_times`` result, keyed by the batch
        members' schedule ids (the pair digest) + the decorrelation seed +
        the fidelity key — the paired hill-climb's accept batches replay
        from here on resume instead of re-running on device.  ``groups``
        (when the round was fused from per-group seeds) rides in the key:
        grouped and ungrouped rounds over the same ids are different
        measurements."""
        b = {"ids": list(ids), "seed": seed,
             "opts": _opts_key(opts), "times": times}
        if groups is not None:
            b["groups"] = [[int(n), int(s)] for n, s in groups]
        line = json.dumps({"batch": b}, sort_keys=True)
        self._check_fence()
        if self._journal_f is None:
            self._journal_f = open(self.journal_path, "a")
        self._journal_f.write(line + "\n")
        self._journal_f.flush()
        os.fsync(self._journal_f.fileno())
        get_metrics().counter("fault.checkpoint.journaled_batches").inc()

    def load_measurements(self, graph, log=None) -> List[
            Tuple[Any, Optional[BenchOpts], BenchResult, str]]:
        """Parse the journal against ``graph``; returns (sequence, opts,
        result, provenance) per complete line.  A torn tail line or a row
        whose ops no longer resolve is skipped with a note — a journal is
        an optimization, never a correctness gate."""
        from tenzing_tpu.core.sequence import Sequence
        from tenzing_tpu.core.serdes import op_from_json

        out = []
        if not os.path.exists(self.journal_path):
            return out
        with open(self.journal_path) as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    j = json.loads(line)
                    if "batch" in j:
                        continue  # batch lines load via load_batches()
                    seq = Sequence(
                        [op_from_json(oj, graph) for oj in j["ops"]])
                    out.append((seq, _opts_from_key(j["opts"]),
                                _result_from_json(j["result"]),
                                j.get("prov", PROVENANCE_MEASURED)))
                except Exception as e:
                    if log is not None:
                        log(f"checkpoint: journal line {i} skipped "
                            f"({type(e).__name__}: {str(e)[:120]})")
        return out

    def load_batches(self, log=None) -> Dict[Tuple, List[List[float]]]:
        """The journaled batch results keyed by (ids tuple, seed, opts key)
        — no graph resolution needed: batch identity is pure digests.
        Later lines win (a re-run batch supersedes)."""
        out: Dict[Tuple, List[List[float]]] = {}
        if not os.path.exists(self.journal_path):
            return out
        with open(self.journal_path) as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    j = json.loads(line)
                    b = j.get("batch")
                    if b is None:
                        continue
                    ok = b["opts"]
                    key = (tuple(b["ids"]), int(b["seed"]),
                           tuple(ok) if ok is not None else None)
                    if b.get("groups") is not None:
                        key = key + (tuple((int(n), int(s))
                                           for n, s in b["groups"]),)
                    out[key] = [list(ts) for ts in b["times"]]
                except Exception as e:
                    if log is not None:
                        log(f"checkpoint: batch journal line {i} skipped "
                            f"({type(e).__name__}: {str(e)[:120]})")
        return out

    def restore_into(self, caching, graph, log=None) -> int:
        """Pre-populate a ``CachingBenchmarker`` from the journal so every
        already-measured schedule is answered without touching the device.
        Only device measurements restore (see module docstring); later
        journal lines win (a re-measurement supersedes).  Journaled *batch*
        results restore into the first :class:`JournalingBenchmarker` found
        on the wrapper chain (``caching.inner...``), so a resumed paired
        hill-climb replays its accept batches too.  Returns the number of
        per-schedule cache entries installed."""
        n = 0
        for seq, opts, res, prov in self.load_measurements(graph, log=log):
            if prov != PROVENANCE_MEASURED:
                continue
            caching._cache[caching._key(seq, opts)] = res
            n += 1
        get_metrics().counter("fault.checkpoint.restored").inc(n)
        layer = caching
        while layer is not None:
            if isinstance(layer, JournalingBenchmarker):
                batches = self.load_batches(log=log)
                layer._batch_cache.update(batches)
                get_metrics().counter(
                    "fault.checkpoint.restored_batches").inc(len(batches))
                break
            layer = getattr(layer, "inner", None)
        return n

    # -- solver-state snapshot ----------------------------------------------
    def save_state(self, state: Optional[Dict[str, Any]] = None,
                   **merge: Any) -> None:
        """Atomically snapshot solver cursors/config.  ``state`` replaces
        the whole document; keyword arguments merge into the current one —
        each solver updates only its own cursor key."""
        if state is not None:
            self._state = dict(state)
        self._state.update(merge)
        self._check_fence()
        # transient EIO retries in-process through THE shared backoff
        # (same rule as store writes): a failed cursor snapshot would
        # otherwise fail the whole drain attempt, and a restarted
        # member replays the identical injected-fault schedule — the
        # item would poison on a bounded burst instead of outliving it
        from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
        from tenzing_tpu.fault.errors import is_transient_io

        retry_call(
            lambda: atomic_write_json(self.state_path, self._state),
            policy=BackoffPolicy(retries=4, base_secs=0.05, factor=2.0,
                                 max_secs=0.5),
            retry_on=is_transient_io, where="fault.checkpoint.state")

    def load_state(self) -> Optional[Dict[str, Any]]:
        """The last snapshot, digest-verified; None when absent."""
        if not os.path.exists(self.state_path):
            return None
        self._state = read_checked_json(self.state_path)
        return dict(self._state)

    def close(self) -> None:
        if self._journal_f is not None:
            self._journal_f.close()
            self._journal_f = None


class JournalingBenchmarker:
    """Records every successful measurement of the wrapped benchmarker into
    a :class:`SearchCheckpoint` journal.  Sits *inside* the run's
    ``CachingBenchmarker`` (cache hits are already journaled) and *outside*
    the resilient wrapper (only measurements that actually completed are
    journaled; provenance downgraded to ``degraded`` when the resilient
    layer answered from its fallback).

    ``benchmark_batch_times`` — the paired hill-climb's accept primitive —
    is journaled too, keyed by (batch-member schedule ids, seed, fidelity)
    and answered from the restored :attr:`_batch_cache` on resume: a
    resumed climb re-runs **zero** accept batches (the ROADMAP
    paired-resume item).  The driver's verdict batches deliberately bypass
    this wrapper (``bench.py`` calls the resilient layer directly), so the
    final verdict stays freshly measured on every run."""

    def __init__(self, inner, checkpoint: SearchCheckpoint):
        self.inner = inner
        self.checkpoint = checkpoint
        self.rank_coherent = getattr(inner, "rank_coherent", False)
        self._batch_cache: Dict[Tuple, List[List[float]]] = {}
        # journal-answered batch queries (a resumed climb's accept steps):
        # exposed like CachingBenchmarker.hits so budgeted callers
        # (solve/local.py) can treat replayed batches as free
        self.batch_hits = 0
        if hasattr(inner, "benchmark_batch_times"):
            # exposed conditionally, like every wrapper in the stack: the
            # batch protocol is only offered when the wrapped benchmarker
            # has it (hill_climb probes with getattr)
            self.benchmark_batch_times = self._batch_times

    def was_degraded(self, order) -> bool:
        fn = getattr(self.inner, "was_degraded", None)
        return bool(fn(order)) if fn is not None else False

    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        res = self.inner.benchmark(order, opts)
        prov = (PROVENANCE_DEGRADED if self.was_degraded(order)
                else PROVENANCE_MEASURED)
        self.checkpoint.record(order, opts, res, provenance=prov)
        return res

    @staticmethod
    def _batch_key(ids, seed: int, opts: Optional[BenchOpts]) -> Tuple:
        ok = _opts_key(opts)
        return (tuple(ids), int(seed), tuple(ok) if ok is not None else None)

    def _batch_times(self, orders, opts: Optional[BenchOpts] = None,
                     seed: int = 0, times_out=None, group_seeds=None):
        from tenzing_tpu.bench.benchmarker import schedule_id

        ids = [schedule_id(o) for o in orders]
        key = self._batch_key(ids, seed, opts)
        if group_seeds is not None:
            # grouped fusion changes each member's permutation stream, so a
            # grouped round and an ungrouped round with the same (ids, seed)
            # are different measurements — keep their journal keys apart
            key = key + (tuple((int(n), int(s)) for n, s in group_seeds),)
        cached = self._batch_cache.get(key)
        if cached is not None:
            self.batch_hits += 1
            get_metrics().counter("fault.checkpoint.batch_hits").inc()
            times = [list(ts) for ts in cached]
            if times_out is not None:
                for dst, src in zip(times_out, times):
                    dst.clear()
                    dst.extend(src)
                return times_out
            return times
        # only forward group_seeds when grouping is requested: inner
        # benchmarkers that predate fused rounds keep their old signature
        kw = {} if group_seeds is None else {"group_seeds": group_seeds}
        out = self.inner.benchmark_batch_times(orders, opts, seed=seed,
                                               times_out=times_out, **kw)
        recorded = [list(ts) for ts in out]
        self._batch_cache[key] = recorded
        self.checkpoint.record_batch(ids, opts, seed, recorded,
                                     groups=group_seeds)
        return out
