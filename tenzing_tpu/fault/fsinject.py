"""Seeded hostile-filesystem fault injection: the storage-plane chaos
substrate.

``fault/inject.py`` injects at the *benchmark* layer; this module
injects at the *filesystem* layer — the failure modes a shared (NFS-like)
mount actually exhibits, threaded through THE one atomic-write seam
(utils/atomic.py) every store/segment/reqlog/checkpoint/lease/status
writer already funnels through.  Six kinds
(``TENZING_FSINJECT=kind:rate:seed[:param]``, comma-separated to
compose):

* ``eio`` — raises ``OSError(EIO)`` on a write or fsync (flaky disk /
  dropped NFS RPC).  Classified transient (fault/errors.py), so the
  hardened writers retry through THE shared fault/backoff.py.
* ``enospc`` — raises ``OSError(ENOSPC)`` on a write (full disk /
  exhausted quota).  Not retryable on any useful timescale: the serve
  plane degrades to read-only (docs/robustness.md "Disaster recovery").
* ``torn_rename`` — dies (SIGKILL) between the fsynced temp file and
  the link/replace that publishes it: the classic torn-publish crash the
  sealed formats are built to survive.  ``param=1`` raises
  :class:`InjectedTornRename` instead of dying (for in-process tests).
* ``stale_read`` — a read of a just-replaced file returns the
  *previous* complete content, once (NFS attribute-cache staleness).
  The lease protocol's nonce re-read is the correctness-critical
  consumer — this is the lie epoch fencing exists to survive.
* ``mtime_skew`` — observed lease mtimes shift ``param`` seconds into
  the past (default 2.0): a skewed client clock ages a live rival's
  heartbeat, the premature-reclaim hole.
* ``mtime_coarse`` — observed lease mtimes floor to ``param``-second
  granularity (default 1.0): FAT/NFSv2-style coarse timestamps, the
  same hole by truncation.

Draws are **identity-keyed**, mirroring inject.py: each checked op draws
from ``hash(kind:seed:basename:op-counter)`` — per-(kind, file) counters,
not process RNG — so the same write to the same file fails across
restarts, and a chaos run replays under its seed.  For ``eio`` /
``enospc`` / ``stale_read``, an integer ``param`` bounds total fires
(0 = unlimited): a burst-then-recover schedule, which is how the
``store_unwritable`` fire-then-resolve drill is scripted.  Counters
restart with the process, like inject.py's — a restarted member replays
its own fault schedule from the top.

Install in-process with :func:`install`, or export ``TENZING_FSINJECT``
before spawning: utils/atomic.py lazily installs from the environment on
first write, so every subprocess fleet member (supervisor, daemons,
drain children) inherits the hostile filesystem without argv plumbing.
The fencing epoch registry (serve/lease.py — O_EXCL directory entries,
not file content) is deliberately outside the seam: it is the layer the
chaos must not be able to lie to.
"""

from __future__ import annotations

import errno
import os
import signal
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple, Union

from tenzing_tpu.fault.inject import _hash_draw
from tenzing_tpu.utils import atomic as _atomic

FS_KINDS = ("eio", "enospc", "torn_rename", "stale_read", "mtime_skew",
            "mtime_coarse")
# which seam ops each fault kind can fire on (utils/atomic.py checkpoints)
_OPS_OF = {
    "eio": ("write", "fsync"),
    "enospc": ("write",),
    "torn_rename": ("link", "replace"),
}
FSINJECT_ENV = _atomic.FSINJECT_ENV


class InjectedTornRename(OSError):
    """The raise-mode torn rename (``torn_rename`` with ``param=1``):
    the publish step failed after the temp bytes landed.  An OSError so
    the classifier calls it transient — the caller's retry re-publishes."""

    def __init__(self, msg: str):
        super().__init__(errno.EIO, msg)


@dataclass(frozen=True)
class FsInjectSpec:
    """One filesystem-fault channel: ``kind`` at probability ``rate``
    from ``seed``; ``param`` is per-kind (module docstring)."""

    kind: str
    rate: float
    seed: int
    param: float = 0.0


def parse_fs_specs(text: str) -> List[FsInjectSpec]:
    """Parse ``kind:rate:seed[:param]`` (comma-separated).  Errors are
    loud, same rule as inject.py: a typo'd chaos spec silently injecting
    nothing would make a green hostile-fs run meaningless."""
    specs: List[FsInjectSpec] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) not in (3, 4):
            raise ValueError(
                f"fsinject spec {part!r}: want kind:rate:seed[:param]")
        kind, rate_s, seed_s = fields[:3]
        if kind not in FS_KINDS:
            raise ValueError(
                f"fsinject kind {kind!r}: want one of {FS_KINDS}")
        rate = float(rate_s)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fsinject rate {rate!r} not in [0, 1]")
        param = float(fields[3]) if len(fields) == 4 else 0.0
        specs.append(FsInjectSpec(kind=kind, rate=rate, seed=int(seed_s),
                                  param=param))
    if not specs:
        raise ValueError("fsinject: empty spec")
    return specs


def format_fs_specs(specs: List[FsInjectSpec]) -> str:
    """The env-var form of ``specs`` (inverse of :func:`parse_fs_specs`)
    — what a chaos harness exports before spawning its fleet."""
    parts = []
    for s in specs:
        part = f"{s.kind}:{s.rate}:{s.seed}"
        if s.param:
            part += f":{s.param:g}"
        parts.append(part)
    return ",".join(parts)


class FsInjectBackend:
    """The injectable I/O backend utils/atomic.py consults (see module
    docstring).  ``injected`` counts fires per kind — chaos tests assert
    on it to prove the run actually exercised the fault paths."""

    def __init__(self, specs: List[FsInjectSpec]):
        self.specs = list(specs)
        self.injected: Dict[str, int] = {k: 0 for k in FS_KINDS}
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], int] = {}
        self._fires: Dict[int, int] = {}   # spec index -> fires so far
        self._prev: Dict[str, str] = {}    # path -> pre-replace content
        self._snapshot = any(s.kind == "stale_read" for s in self.specs)

    # -- draw machinery ------------------------------------------------------
    def _draw(self, spec: FsInjectSpec, idx: int, base: str) -> bool:
        """One identity-keyed coin flip; counts the (kind, file) op and
        honors the channel's max-fires bound."""
        with self._lock:
            n = self._counters.get((spec.kind, base), 0)
            self._counters[(spec.kind, base)] = n + 1
            if spec.param and spec.kind in ("eio", "enospc", "stale_read") \
                    and self._fires.get(idx, 0) >= int(spec.param):
                return False  # channel burst exhausted: quiet from here on
            if _hash_draw(f"{spec.kind}:{spec.seed}:{base}:{n}") >= spec.rate:
                return False
            self._fires[idx] = self._fires.get(idx, 0) + 1
        self._record(spec.kind, base)
        return True

    def _record(self, kind: str, base: str) -> None:
        self.injected[kind] += 1
        try:
            from tenzing_tpu.obs.metrics import get_metrics
            from tenzing_tpu.obs.tracer import get_tracer

            get_metrics().counter(f"fault.fsinjected.{kind}").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event("fault.fsinjected", kind=kind, file=base)
        except Exception:
            pass  # telemetry must never turn an injected fault into a real one

    # -- seam checkpoints (utils/atomic.py) ----------------------------------
    def check(self, op: str, path: str) -> None:
        """The write-path checkpoint: ``op`` is about to run against the
        (final) ``path``.  May raise OSError(EIO/ENOSPC), raise
        :class:`InjectedTornRename`, or SIGKILL this process."""
        base = os.path.basename(path)
        if self._snapshot and op in ("link", "replace"):
            self._snapshot_prev(path)
        for idx, spec in enumerate(self.specs):
            if op not in _OPS_OF.get(spec.kind, ()):
                continue
            if not self._draw(spec, idx, base):
                continue
            if spec.kind == "torn_rename":
                if spec.param:
                    raise InjectedTornRename(
                        f"injected torn rename (fsinject {base})")
                # the real thing: die with the temp bytes on disk and the
                # publish not yet linked — the successor finds the torn state
                os.kill(os.getpid(), signal.SIGKILL)
            code = errno.ENOSPC if spec.kind == "enospc" else errno.EIO
            raise OSError(code, f"injected {spec.kind} (fsinject {base} "
                                f"op {op})")

    def _snapshot_prev(self, path: str) -> None:
        """Remember the content a replace is about to supersede — the
        stale version a later injected read will serve."""
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            return  # first publish: nothing older to serve stale
        with self._lock:
            self._prev[path] = text

    def maybe_stale_json(self, path: str) -> Optional[Any]:
        """The read-path checkpoint: the previous complete JSON content
        of ``path``, served at most once per superseded version, when a
        ``stale_read`` draw fires — else None (read the real file)."""
        import json

        if not self._snapshot or path not in self._prev:
            return None
        base = os.path.basename(path)
        for idx, spec in enumerate(self.specs):
            if spec.kind != "stale_read":
                continue
            if not self._draw(spec, idx, base):
                continue
            with self._lock:
                text = self._prev.pop(path, None)
            if text is None:
                return None
            try:
                return json.loads(text)
            except ValueError:
                return None  # stale version was torn: the real read decides
        return None

    def observe_mtime(self, path: str, mtime: float) -> float:
        """The clock checkpoint: what a lease-expiry check *observes* for
        ``path``'s mtime — skewed and/or coarsened when draws fire."""
        base = os.path.basename(path)
        out = mtime
        for idx, spec in enumerate(self.specs):
            if spec.kind == "mtime_coarse":
                if self._draw(spec, idx, base):
                    gran = spec.param or 1.0
                    out = (out // gran) * gran
            elif spec.kind == "mtime_skew":
                if self._draw(spec, idx, base):
                    out -= (spec.param or 2.0)
        return out


def install(specs: Union[str, List[FsInjectSpec]]) -> FsInjectBackend:
    """Install a hostile-filesystem backend behind utils/atomic.py's
    seam; returns it (tests assert on ``backend.injected``)."""
    if isinstance(specs, str):
        specs = parse_fs_specs(specs)
    backend = FsInjectBackend(specs)
    _atomic.set_io_backend(backend)
    return backend


def uninstall() -> None:
    """Restore the well-behaved filesystem."""
    _atomic.set_io_backend(None)


def installed() -> Optional[FsInjectBackend]:
    return _atomic.io_backend()


def install_from_env() -> Optional[FsInjectBackend]:
    """Install from ``$TENZING_FSINJECT`` (the subprocess-inheritance
    path — utils/atomic.py calls this lazily on first write)."""
    text = os.environ.get(FSINJECT_ENV)
    if not text:
        return None
    return install(text)
