"""Seeded fault injection: the chaos substrate for tests and CI.

:class:`FaultInjectingBenchmarker` wraps any benchmarker and injects
failures *deterministically* from seeded RNGs — the same seed replays the
same fault schedule, so a chaos run is a reproducible experiment, not a
flake generator.  Four kinds (``bench.py --inject-faults kind:rate:seed``,
comma-separated to compose):

* ``transient`` — raises :class:`InjectedTransientError` on a seeded
  per-call coin flip (classified transient → the resilient wrapper retries).
* ``hang`` — sleeps ``hang_secs`` before proceeding on a seeded per-call
  coin flip (the stalled-RPC simulation): with a watchdog shorter than the
  hang, the wrapper's :class:`MeasurementTimeout` path fires; without one,
  the call is merely slow — both are realistic tunnel behaviors.
* ``deterministic`` — fails by *schedule identity* (a hash of the schedule
  id and the seed, not a per-call draw): the same ``rate`` fraction of
  candidates always fails, exactly like a candidate that genuinely cannot
  compile — the quarantine's target.
* ``device_lost`` — raises :class:`~tenzing_tpu.fault.errors.DeviceLostError`
  on a seeded per-call coin flip (the degradation drill).

Injection draws are per-process: the harness is a single-host test/CI tool
(multi-host chaos would need rank-agreed draws to be meaningful).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from random import Random
from typing import Dict, List, Optional

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, schedule_id
from tenzing_tpu.fault.errors import (
    DeterministicScheduleError,
    DeviceLostError,
    TransientError,
)
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer

KINDS = ("transient", "hang", "deterministic", "device_lost")


class InjectedTransientError(TransientError):
    """A seeded injected tunnel flake."""


class InjectedDeterministicError(DeterministicScheduleError):
    """A seeded injected always-broken candidate."""


@dataclass(frozen=True)
class InjectSpec:
    """One injection channel: ``kind`` at probability ``rate`` from ``seed``."""

    kind: str
    rate: float
    seed: int


def parse_inject_specs(text: str) -> List[InjectSpec]:
    """Parse ``kind:rate:seed[,kind:rate:seed...]`` (the --inject-faults
    grammar).  Errors are loud: a typo'd chaos spec silently injecting
    nothing would make a green chaos run meaningless."""
    specs: List[InjectSpec] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"--inject-faults spec {part!r}: want kind:rate:seed")
        kind, rate_s, seed_s = fields
        if kind not in KINDS:
            raise ValueError(
                f"--inject-faults kind {kind!r}: want one of {KINDS}")
        rate = float(rate_s)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"--inject-faults rate {rate!r} not in [0, 1]")
        specs.append(InjectSpec(kind=kind, rate=rate, seed=int(seed_s)))
    if not specs:
        raise ValueError("--inject-faults: empty spec")
    return specs


def _schedule_fails(sid: str, spec: InjectSpec) -> bool:
    """Deterministic by schedule identity: hash(sid, seed) under rate."""
    h = hashlib.sha256(f"{sid}:{spec.seed}".encode()).digest()
    draw = int.from_bytes(h[:8], "big") / float(1 << 64)
    return draw < spec.rate


class FaultInjectingBenchmarker:
    """Chaos wrapper (see module docstring).  ``injected`` counts injections
    per kind; ``calls`` counts benchmark queries — the chaos tests assert on
    both to prove the run actually exercised the fault paths."""

    def __init__(self, inner, specs: List[InjectSpec],
                 hang_secs: float = 60.0, sleep=time.sleep):
        self.inner = inner
        self.specs = list(specs)
        self.hang_secs = hang_secs
        self._sleep = sleep
        self._rngs = {id(s): Random(s.seed) for s in self.specs}
        self.calls = 0
        self.injected: Dict[str, int] = {k: 0 for k in KINDS}
        # forwarded so a wrapped EmpiricalBenchmarker still offers the batch
        # protocol (injection applies per benchmark() query only: batches
        # are the final verdict path, which chaos leaves untouched)
        if hasattr(inner, "benchmark_batch_times"):
            self.benchmark_batch_times = inner.benchmark_batch_times

    def _record(self, kind: str, sid: str) -> None:
        self.injected[kind] += 1
        get_metrics().counter(f"fault.injected.{kind}").inc()
        tr = get_tracer()
        if tr.enabled:
            tr.event("fault.injected", kind=kind, schedule=sid)

    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        self.calls += 1
        sid = schedule_id(order)
        for spec in self.specs:
            if spec.kind == "deterministic":
                if _schedule_fails(sid, spec):
                    self._record("deterministic", sid)
                    raise InjectedDeterministicError(
                        f"injected deterministic failure (schedule {sid})")
            elif self._rngs[id(spec)].random() < spec.rate:
                if spec.kind == "transient":
                    self._record("transient", sid)
                    raise InjectedTransientError(
                        f"injected transient failure (call {self.calls})")
                if spec.kind == "hang":
                    self._record("hang", sid)
                    self._sleep(self.hang_secs)
                elif spec.kind == "device_lost":
                    self._record("device_lost", sid)
                    raise DeviceLostError(
                        f"injected device loss (call {self.calls})")
        return self.inner.benchmark(order, opts)
