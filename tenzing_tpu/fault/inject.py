"""Seeded fault injection: the chaos substrate for tests and CI.

:class:`FaultInjectingBenchmarker` wraps any benchmarker and injects
failures *deterministically* from seeded draws — the same seed replays the
same fault schedule, so a chaos run is a reproducible experiment, not a
flake generator.  Five kinds (``bench.py --inject-faults kind:rate:seed``,
comma-separated to compose):

* ``transient`` — raises :class:`InjectedTransientError` on a seeded
  per-attempt coin flip (classified transient → the resilient wrapper
  retries).
* ``hang`` — sleeps ``hang_secs`` before proceeding on a seeded per-attempt
  coin flip (the stalled-RPC simulation): with a watchdog shorter than the
  hang, the wrapper's :class:`MeasurementTimeout` path fires; without one,
  the call is merely slow — both are realistic tunnel behaviors.
* ``deterministic`` — fails by *schedule identity* (a hash of the schedule
  id and the seed, not a per-attempt draw): the same ``rate`` fraction of
  candidates always fails, exactly like a candidate that genuinely cannot
  compile — the quarantine's target.
* ``device_lost`` — raises :class:`~tenzing_tpu.fault.errors.DeviceLostError`
  on a seeded per-attempt coin flip (the degradation drill).
* ``corrupt`` — **mutates the candidate schedule** (drops or reorders one
  of its sync ops, :func:`corrupt_schedule`) by schedule identity before
  passing it on: the simulation of a schedule-handling bug — exactly what
  the independent soundness verifier (tenzing_tpu/verify) exists to catch.
  A corrupt injector therefore belongs *outside* the
  :class:`~tenzing_tpu.fault.resilient.ResilientBenchmarker` whose
  ``verifier`` gate must see (and quarantine) the mutated schedule;
  ``bench.py`` splits the spec list accordingly.  Only mutations the
  configured ``unsound_check`` confirms detectable count as injected —
  dropping a genuinely redundant sync produces a still-correct schedule,
  which is no fault at all.

Injection draws are **rank-agreed by construction** (the multi-host chaos
item of ROADMAP.md): per-attempt kinds draw from a hash of (kind, seed,
schedule identity, per-schedule attempt counter) instead of per-process RNG
state.  Every rank benchmarks the same broadcast schedule sequence, so the
counters — and with them every draw — agree across hosts without
communication, and the rank-coherent ``agree_fault`` protocol
(fault/resilient.py) can be chaos-tested under a real control plane
(tests/test_multihost.py).  The counters also survive nothing: a restarted
process re-counts from zero, which is exactly what the deterministic
search's resume (re-executing the same query sequence) needs to replay the
same faults.
"""

from __future__ import annotations

import hashlib
import random as _random
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, schedule_id
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import SyncOp
from tenzing_tpu.fault.errors import (
    DeterministicScheduleError,
    DeviceLostError,
    TransientError,
)
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer

KINDS = ("transient", "hang", "deterministic", "device_lost", "corrupt")


class InjectedTransientError(TransientError):
    """A seeded injected tunnel flake."""


class InjectedDeterministicError(DeterministicScheduleError):
    """A seeded injected always-broken candidate."""


@dataclass(frozen=True)
class InjectSpec:
    """One injection channel: ``kind`` at probability ``rate`` from ``seed``."""

    kind: str
    rate: float
    seed: int


def parse_inject_specs(text: str) -> List[InjectSpec]:
    """Parse ``kind:rate:seed[,kind:rate:seed...]`` (the --inject-faults
    grammar).  Errors are loud: a typo'd chaos spec silently injecting
    nothing would make a green chaos run meaningless."""
    specs: List[InjectSpec] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"--inject-faults spec {part!r}: want kind:rate:seed")
        kind, rate_s, seed_s = fields
        if kind not in KINDS:
            raise ValueError(
                f"--inject-faults kind {kind!r}: want one of {KINDS}")
        rate = float(rate_s)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"--inject-faults rate {rate!r} not in [0, 1]")
        specs.append(InjectSpec(kind=kind, rate=rate, seed=int(seed_s)))
    if not specs:
        raise ValueError("--inject-faults: empty spec")
    return specs


def _hash_draw(material: str) -> float:
    """Uniform [0, 1) draw from a content hash — identical on every rank
    and across restarts for the same material."""
    h = hashlib.sha256(material.encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


def _schedule_fails(sid: str, spec: InjectSpec) -> bool:
    """Deterministic by schedule identity: hash(sid, seed) under rate."""
    return _hash_draw(f"{sid}:{spec.seed}") < spec.rate


def _attempt_fires(sid: str, attempt: int, spec: InjectSpec) -> bool:
    """Per-attempt draw, rank-agreed: keyed on the schedule identity, the
    per-schedule attempt counter and the channel — not on process-local RNG
    state (see module docstring)."""
    return _hash_draw(f"{spec.kind}:{spec.seed}:{sid}:{attempt}") < spec.rate


# -- schedule corruption ---------------------------------------------------


def corrupt_schedule(
    order: Sequence,
    seed: int,
    unsound_check: Optional[Callable[[Sequence], bool]] = None,
) -> Optional[Sequence]:
    """A mutated copy of ``order`` with one sync op dropped or deferred
    (moved behind the rest of the schedule) — the two ways schedule-handling
    code plausibly mangles synchronization — or None when no mutation makes
    the schedule detectably unsound.

    Mutation candidates are tried in a ``seed``-deterministic shuffle;
    ``unsound_check(mutated) -> bool`` decides which mutations count (the
    chaos tests pass the EventSynchronizer-derived ground truth so the
    verifier under test is not consulted; ``bench.py`` passes the deployed
    verifier so a chaos run never silently injects a no-op).  Without a
    check, the first candidate mutation is returned blind."""
    ops = order.vector()
    sync_pos = [i for i, op in enumerate(ops) if isinstance(op, SyncOp)]
    if not sync_pos:
        return None
    cands = [("drop", i) for i in sync_pos]
    # defer: move the sync to the end of the schedule (past every op it was
    # protecting; a wait deferred past its dependents, a record past its
    # waiters — both reorderings real code could commit)
    cands += [("defer", i) for i in sync_pos if i != len(ops) - 1]
    rng = _random.Random(f"{seed}:{schedule_id(order)}")
    rng.shuffle(cands)
    for kind, i in cands:
        if kind == "drop":
            mut = ops[:i] + ops[i + 1:]
        else:
            mut = ops[:i] + ops[i + 1:] + [ops[i]]
        seq = Sequence(mut)
        if unsound_check is None or unsound_check(seq):
            return seq
    return None


class FaultInjectingBenchmarker:
    """Chaos wrapper (see module docstring).  ``injected`` counts injections
    per kind; ``calls`` counts benchmark queries — the chaos tests assert on
    both to prove the run actually exercised the fault paths.  ``corrupted``
    maps each mutated schedule's original id to the mutated id, so tests can
    hold the verifier to account for every mutation."""

    def __init__(self, inner, specs: List[InjectSpec],
                 hang_secs: float = 60.0, sleep=time.sleep,
                 unsound_check: Optional[Callable[[Sequence], bool]] = None,
                 exempt_ids: Optional[set] = None):
        self.inner = inner
        self.specs = list(specs)
        self.hang_secs = hang_secs
        self._sleep = sleep
        self._attempts: Dict[str, int] = {}  # sid -> benchmark-call count
        self.unsound_check = unsound_check
        # schedule ids exempt from the identity-keyed CANDIDATE-fault kinds
        # (deterministic, corrupt): bench.py registers its naive baseline —
        # an identity draw deterministically breaking the baseline would
        # kill every run under that seed before the search starts, which is
        # no chaos experiment at all.  Per-attempt tunnel-fault kinds
        # (transient/hang/device_lost) still apply: baselines ride the same
        # flaky tunnel as everything else and their failures retry.
        self.exempt_ids: set = set(exempt_ids) if exempt_ids else set()
        self.calls = 0
        self.injected: Dict[str, int] = {k: 0 for k in KINDS}
        self.corrupted: Dict[str, str] = {}  # original sid -> mutated sid
        # forwarded so a wrapped EmpiricalBenchmarker still offers the batch
        # protocol (injection applies per benchmark() query only: batches
        # are the final verdict path, which chaos leaves untouched)
        if hasattr(inner, "benchmark_batch_times"):
            self.benchmark_batch_times = inner.benchmark_batch_times
        # a corrupt injector stacked OUTSIDE the resilient wrapper must not
        # hide the inner stack's rank-coherence from the solvers
        self.rank_coherent = getattr(inner, "rank_coherent", False)

    def was_degraded(self, order) -> bool:
        """Degradation provenance passes through the injector — a corrupt
        injector stacked between JournalingBenchmarker and the resilient
        wrapper must not launder fallback answers into ``measured`` journal
        rows."""
        fn = getattr(self.inner, "was_degraded", None)
        return bool(fn(order)) if fn is not None else False

    def _record(self, kind: str, sid: str) -> None:
        self.injected[kind] += 1
        get_metrics().counter(f"fault.injected.{kind}").inc()
        tr = get_tracer()
        if tr.enabled:
            tr.event("fault.injected", kind=kind, schedule=sid)

    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        self.calls += 1
        sid = schedule_id(order)
        attempt = self._attempts.get(sid, 0)
        self._attempts[sid] = attempt + 1
        for spec in self.specs:
            if spec.kind == "deterministic":
                if sid not in self.exempt_ids and _schedule_fails(sid, spec):
                    self._record("deterministic", sid)
                    raise InjectedDeterministicError(
                        f"injected deterministic failure (schedule {sid})")
            elif spec.kind == "corrupt":
                if (sid not in self.exempt_ids and _schedule_fails(sid, spec)
                        and isinstance(order, Sequence)):
                    mutated = corrupt_schedule(order, spec.seed,
                                               self.unsound_check)
                    if mutated is not None:
                        self._record("corrupt", sid)
                        self.corrupted[sid] = schedule_id(mutated)
                        order = mutated
            elif _attempt_fires(sid, attempt, spec):
                if spec.kind == "transient":
                    self._record("transient", sid)
                    raise InjectedTransientError(
                        f"injected transient failure (schedule {sid} "
                        f"attempt {attempt})")
                if spec.kind == "hang":
                    self._record("hang", sid)
                    self._sleep(self.hang_secs)
                elif spec.kind == "device_lost":
                    self._record("device_lost", sid)
                    raise DeviceLostError(
                        f"injected device loss (schedule {sid} "
                        f"attempt {attempt})")
        return self.inner.benchmark(order, opts)
