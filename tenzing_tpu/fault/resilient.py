"""ResilientBenchmarker: classified retries, watchdog, quarantine,
rank-coherent failure agreement, graceful degradation.

Wraps any benchmarker (the Benchmarker protocol: ``benchmark(order, opts)
-> BenchResult``; ``benchmark_batch_times`` forwarded when present) with the
fault policy of docs/robustness.md:

* **watchdog** — each attempt runs on a daemon worker thread bounded by a
  wall-clock ``timeout_secs``; a hung measurement (stuck collective, dead
  tunnel that never errors) surfaces as
  :class:`~tenzing_tpu.fault.errors.MeasurementTimeout` instead of blocking
  the search forever.  The timed-out worker is *abandoned* (Python cannot
  interrupt a thread blocked in C) — safe for a dead RPC, and the retry
  dispatches fresh.
* **classification** (fault/errors.py): transient → bounded retry with
  exponential backoff + jitter (the shared ``BackoffPolicy``); deterministic
  → persistent quarantine (fault/quarantine.py) + raise — the same broken
  candidate is never measured twice, even across restarts; device-lost →
  degrade or escalate.
* **rank-coherent agreement** — before each attempt and after it, every rank
  allreduce-maxes a fault code (``ControlPlane.agree_fault``).  A failure on
  one rank therefore becomes a failure on *all* ranks at the same attempt
  boundary: ranks retry together, quarantine together, and degrade
  together, instead of one rank raising while its peers deadlock in the
  next collective.  The watchdog is what guarantees a hung rank eventually
  *reaches* the agreement point.
* **soundness gate** — with a ``verifier`` configured (the independent
  happens-before checker, tenzing_tpu/verify), every schedule is verified
  *before* it is measured: an unsound schedule — a dropped or mis-ordered
  sync, whether from a synthesizer bug or injected corruption — is a
  deterministic fault discovered for free (no device time), quarantined
  with a ``verify.unsound`` obs event, and refused as
  :class:`~tenzing_tpu.fault.errors.UnsoundScheduleError`.  A
  fast-but-wrong schedule can therefore never produce a measurement.
* **graceful degradation** — on device loss with a ``fallback`` benchmarker
  configured (e.g. the PR 2 learned surrogate), the wrapper flips to
  answering every subsequent query from the fallback, records which
  schedules were answered that way (:meth:`was_degraded` — dump paths tag
  those rows ``fid=degraded`` so they never pass as measurements), and the
  search finishes instead of dying.  Without a fallback, device loss raises
  :class:`~tenzing_tpu.fault.errors.DeviceLostError`.

``rank_coherent = True`` advertises the agreement protocol to the solvers:
their reject paths may treat a benchmark failure as a dead-end candidate
even under a multi-host control plane (solve/mcts, solve/dfs, solve/local),
because every rank saw the same failure at the same point.
"""

from __future__ import annotations

import random as _random
import threading
import time
from typing import List, Optional

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, schedule_id
from tenzing_tpu.fault.backoff import BackoffPolicy
from tenzing_tpu.fault.errors import (
    DeviceLostError,
    FaultClass,
    MeasurementTimeout,
    QuarantinedScheduleError,
    UnsoundScheduleError,
    classify_error,
)
from tenzing_tpu.fault.quarantine import Quarantine
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.progress import get_reporter
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.parallel.control_plane import ControlPlane, default_control_plane


class ResilientBenchmarker:
    """Fault-policy wrapper around a benchmarker (see module docstring)."""

    rank_coherent = True

    def __init__(
        self,
        inner,
        control_plane: Optional[ControlPlane] = None,
        timeout_secs: Optional[float] = None,
        policy: Optional[BackoffPolicy] = None,
        quarantine: Optional[Quarantine] = None,
        fallback=None,
        sleep=time.sleep,
        seed: int = 0,
        verifier=None,
    ):
        self.inner = inner
        self.cp = control_plane if control_plane is not None else (
            default_control_plane())
        self.timeout_secs = timeout_secs
        self.policy = policy if policy is not None else BackoffPolicy()
        self.quarantine = quarantine if quarantine is not None else Quarantine()
        self.fallback = fallback
        # independent soundness gate (tenzing_tpu/verify.ScheduleVerifier):
        # an unsound schedule is a deterministic fault discovered WITHOUT
        # touching the device — quarantined and refused, never measured.
        # Verification is a pure function of the (broadcast-identical)
        # schedule, so every rank reaches the same verdict at the same
        # point: no agreement round needed, the protocol stays in lockstep.
        self.verifier = verifier
        self._sleep = sleep
        self._rng = _random.Random(seed)
        self.degraded = False
        self._degraded_keys: set = set()
        # the batch protocol is only offered when the wrapped benchmarker
        # has it — hill_climb's paired mode probes with getattr
        if hasattr(inner, "benchmark_batch_times"):
            self.benchmark_batch_times = self._batch_times

    # -- provenance --------------------------------------------------------
    def was_degraded(self, order) -> bool:
        """True if a query for ``order`` was answered by the fallback after
        device loss — dump paths tag such rows ``fid=degraded``."""
        return schedule_id(order) in self._degraded_keys

    # -- soundness gate ----------------------------------------------------
    def _check_sound(self, order) -> None:
        """Refuse an unsound schedule before it reaches the device: the
        independent verifier's rejection is classified deterministic (the
        schedule is wrong, not unlucky), quarantined, and raised as
        :class:`UnsoundScheduleError` with the minimal witness."""
        if self.verifier is None:
            return
        verdict = self.verifier(order)
        if verdict.ok:
            return
        from tenzing_tpu.verify.soundness import report_unsound

        report_unsound("resilient.benchmark", order, verdict)
        err = UnsoundScheduleError(
            f"schedule fails soundness verification: {verdict.witness()}")
        self.quarantine.add(order, err, FaultClass.DETERMINISTIC)
        raise err

    # -- watchdog ----------------------------------------------------------
    def _call_with_timeout(self, fn, *args, **kwargs):
        if self.timeout_secs is None:
            return fn(*args, **kwargs)
        out: dict = {}
        done = threading.Event()

        def work():  # pragma: no cover - trivial trampoline
            try:
                out["res"] = fn(*args, **kwargs)
            except BaseException as e:
                out["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=work, daemon=True, name="tz-measure")
        t.start()
        if not done.wait(self.timeout_secs):
            raise MeasurementTimeout(
                f"measurement exceeded {self.timeout_secs}s wall clock "
                "(watchdog)")
        if "exc" in out:
            raise out["exc"]
        return out["res"]

    # -- degradation -------------------------------------------------------
    def _degrade_or_raise(self, order, exc: Optional[BaseException]):
        if self.fallback is None:
            get_metrics().counter("fault.device_lost_fatal").inc()
            err = DeviceLostError(
                "device lost and no fallback benchmarker configured")
            if exc is not None:
                raise err from exc
            raise err
        if not self.degraded:
            self.degraded = True
            get_metrics().counter("fault.degraded").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event("fault.degraded",
                         error=type(exc).__name__ if exc else None,
                         message=str(exc)[:200] if exc else None)
            get_reporter().warn(
                "fault: device lost — degrading to fallback benchmarker; "
                "subsequent results carry fid=degraded provenance",
                error=type(exc).__name__ if exc else None,
            )

    def _answer_degraded(self, order, opts) -> BenchResult:
        res = self.fallback.benchmark(order, opts)
        self._degraded_keys.add(schedule_id(order))
        get_metrics().counter("fault.degraded_answers").inc()
        return res

    # -- the resilient measurement loop ------------------------------------
    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        if self.degraded:
            # all ranks entered degradation together (the agreement below),
            # so the degraded path runs no collectives: the device — and
            # with it the cross-host barrier fabric — may be gone
            return self._answer_degraded(order, opts)
        rec = self.quarantine.check(order)
        if rec is not None:
            get_metrics().counter("fault.quarantine_hits").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event("fault.quarantine_hit",
                         schedule=self.quarantine.key(order),
                         error=rec.get("error"))
            raise QuarantinedScheduleError(
                f"schedule quarantined ({rec.get('error')}: "
                f"{rec.get('message', '')[:200]})")
        self._check_sound(order)
        tr = get_tracer()
        reg = get_metrics()
        attempts = self.policy.retries + 1
        for attempt in range(attempts):
            # pre-attempt agreement: aligns attempt generations — every rank
            # enters the measurement (or its failure handling) together
            self.cp.agree_fault(0)
            res: Optional[BenchResult] = None
            exc: Optional[BaseException] = None
            code = 0
            try:
                res = self._call_with_timeout(
                    self.inner.benchmark, order, opts)
            except (KeyboardInterrupt, SystemExit):
                raise  # an interrupt is for the trap layer, not the retrier
            except BaseException as e:
                exc = e
                code = FaultClass.CODES[classify_error(e)]
            # post-attempt agreement: the worst fault class on any rank wins
            agreed = int(self.cp.agree_fault(code))
            if agreed == FaultClass.CODES[FaultClass.OK]:
                return res  # type: ignore[return-value]
            cls = FaultClass.FROM_CODE.get(agreed, FaultClass.DETERMINISTIC)
            reg.counter(f"fault.errors.{cls}").inc()
            if tr.enabled:
                tr.event(
                    "fault.error", where="bench.benchmark",
                    schedule=schedule_id(order), attempt=attempt + 1,
                    error=type(exc).__name__ if exc else "peer-rank",
                    error_class=cls,
                    message=str(exc)[:200] if exc else None,
                )
            if cls == FaultClass.DEVICE_LOST:
                self._degrade_or_raise(order, exc)
                return self._answer_degraded(order, opts)
            if cls == FaultClass.DETERMINISTIC:
                self.quarantine.add(
                    order,
                    exc if exc is not None else RuntimeError("peer-rank failure"),
                    cls,
                )
                if exc is not None:
                    raise exc
                raise QuarantinedScheduleError(
                    "deterministic failure on a peer rank")
            # transient: bounded retry with backoff + jitter
            if attempt == attempts - 1:
                if exc is not None:
                    raise exc
                raise MeasurementTimeout(
                    "transient failure on a peer rank; retries exhausted")
            delay = self.policy.delay(attempt, self._rng)
            reg.counter("fault.retries").inc()
            if tr.enabled:
                tr.event("fault.retry", where="bench.benchmark",
                         schedule=schedule_id(order), attempt=attempt + 1,
                         error=type(exc).__name__ if exc else "peer-rank",
                         error_class=cls, delay_secs=round(delay, 4))
            if delay > 0.0:
                self._sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover

    # -- decorrelated batches ----------------------------------------------
    def _batch_times(
        self,
        orders: List,
        opts: Optional[BenchOpts] = None,
        seed: int = 0,
        times_out: Optional[List[List[float]]] = None,
        group_seeds=None,
    ) -> List[List[float]]:
        """``benchmark_batch_times`` with the watchdog (scaled: a batch is
        ``len(orders)`` measurement series) and transient-class retries.
        No quarantine — a batch mixes schedules, so a deterministic failure
        cannot be attributed to one candidate and simply raises.

        ``times_out`` handling depends on the watchdog.  Without one, the
        caller's lists are passed straight through (live partial data for
        the trap handler, the DFS partial-dump contract).  With a watchdog,
        a timed-out attempt abandons a worker thread that still holds
        references to whatever lists the inner call received — so each
        attempt gets FRESH private lists and the caller's are only
        clear()-ed + filled from a completed attempt's result: an abandoned
        worker can never interleave stale appends into the series the
        caller reads (iteration alignment is what paired comparisons trust).
        Trap dumps during a supervised batch then only see completed
        attempts, which is exactly the data that is actually valid."""
        if self.degraded:
            raise DeviceLostError(
                "batch benchmarking unavailable in degraded mode")
        # soundness-gate every member up front: unlike a runtime batch
        # failure, verification attributes the fault to ONE schedule, so
        # the unsound member is quarantined before anything is measured
        for order in orders:
            self._check_sound(order)
        timeout = (None if self.timeout_secs is None
                   else self.timeout_secs * max(1, len(orders)))
        tr = get_tracer()
        reg = get_metrics()
        attempts = self.policy.retries + 1
        for attempt in range(attempts):
            self.cp.agree_fault(0)
            exc = None
            code = 0
            out: Optional[List[List[float]]] = None
            inner_times = (times_out if timeout is None else
                           ([[] for _ in orders]
                            if times_out is not None else None))
            try:
                # inner benchmarkers that predate fused rounds keep their
                # old signature: forward group_seeds only when grouping
                gkw = {} if group_seeds is None else {
                    "group_seeds": group_seeds}
                out = self._call_with_timeout_scaled(
                    timeout, self.inner.benchmark_batch_times,
                    orders, opts, seed=seed, times_out=inner_times, **gkw)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                exc = e
                code = FaultClass.CODES[classify_error(e)]
            agreed = int(self.cp.agree_fault(code))
            if agreed == FaultClass.CODES[FaultClass.OK]:
                if timeout is not None and times_out is not None:
                    for dst, src in zip(times_out, out):
                        dst.clear()
                        dst.extend(src)
                    return times_out
                return out  # type: ignore[return-value]
            cls = FaultClass.FROM_CODE.get(agreed, FaultClass.DETERMINISTIC)
            reg.counter(f"fault.errors.{cls}").inc()
            if tr.enabled:
                tr.event("fault.error", where="bench.batch",
                         attempt=attempt + 1,
                         error=type(exc).__name__ if exc else "peer-rank",
                         error_class=cls,
                         message=str(exc)[:200] if exc else None)
            if cls != FaultClass.TRANSIENT or attempt == attempts - 1:
                if cls == FaultClass.DEVICE_LOST:
                    self._degrade_or_raise(None, exc)
                    raise DeviceLostError(
                        "device lost mid-batch; batch cannot degrade")
                if exc is not None:
                    raise exc
                raise MeasurementTimeout("peer-rank batch failure")
            if times_out is not None:
                for ts in times_out:
                    ts.clear()
            delay = self.policy.delay(attempt, self._rng)
            reg.counter("fault.retries").inc()
            if tr.enabled:
                tr.event("fault.retry", where="bench.batch",
                         attempt=attempt + 1, delay_secs=round(delay, 4),
                         error=type(exc).__name__ if exc else "peer-rank")
            if delay > 0.0:
                self._sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover

    def _call_with_timeout_scaled(self, timeout, fn, *args, **kwargs):
        saved, self.timeout_secs = self.timeout_secs, timeout
        try:
            return self._call_with_timeout(fn, *args, **kwargs)
        finally:
            self.timeout_secs = saved
