"""Failure taxonomy of the measurement loop.

The empirical loop — compile a candidate schedule, run it fenced on real
hardware through a remote PJRT tunnel, reduce across hosts — fails in three
fundamentally different ways, and each demands a different response
(docs/robustness.md):

* **transient** — the tunnel dropped an RPC, a socket reset, a watchdog
  timeout on a hung fetch: *the measurement* failed, not the schedule.
  Retrying (with backoff, fault/backoff.py) is correct and usually works.
* **deterministic** — the *schedule* is broken: it does not compile, its
  liveness exceeds device memory, a shape contract is violated.  Retrying
  re-pays the failing compile for the same verdict; the candidate is
  quarantined (fault/quarantine.py) so it is never measured again, even
  across process restarts.
* **device_lost** — the chip is gone (reboot, preemption, tunnel torn down
  for good).  No retry can help; the runtime either degrades to recorded +
  predicted answers (fault/resilient.py) or aborts.

:func:`classify_error` maps an arbitrary exception to one of these classes.
Explicit marker types (raised by the fault layer itself and by the
fault-injection harness) classify by ``isinstance``; everything else by
exception type and message patterns.  Unknown errors default to
**deterministic**: an unrecognized failure is most often a broken candidate,
and mis-classifying a transient as deterministic costs one quarantined
candidate, while mis-classifying a deterministic as transient costs
``retries`` failing compiles *per encounter, forever*.
"""

from __future__ import annotations

import errno as _errno


class FaultClass:
    """The three failure classes, ordered by severity (the rank-agreement
    protocol allreduce-maxes the numeric codes, so the *worst* class seen on
    any rank wins — fault/resilient.py)."""

    OK = "ok"
    TRANSIENT = "transient"
    DETERMINISTIC = "deterministic"
    DEVICE_LOST = "device_lost"

    CODES = {OK: 0, TRANSIENT: 1, DETERMINISTIC: 2, DEVICE_LOST: 3}
    FROM_CODE = {v: k for k, v in CODES.items()}


class TransientError(RuntimeError):
    """A measurement attempt failed for reasons unrelated to the schedule
    (tunnel/RPC flake); retry with backoff."""


class MeasurementTimeout(TransientError):
    """The watchdog wall-clock bound fired: the measurement hung (a stuck
    collective, a dead tunnel that never errors).  Transient — the retry
    gets a fresh dispatch — but also the deadlock breaker: a rank that
    would have blocked forever in a barrier instead reports a fault code."""


class StoreLockTimeout(TransientError):
    """The serving store's manifest lock stayed contended past the bounded
    backoff (serve/segments.py: every manifest read-modify-write takes a
    non-blocking flock through fault/backoff.py).  Transient by nature —
    the rival writer will finish; retrying the whole operation later is
    correct, waiting forever inside a serving request is not."""


class DeterministicScheduleError(RuntimeError):
    """The schedule itself is broken (compile/shape/liveness); quarantine."""


class QuarantinedScheduleError(DeterministicScheduleError):
    """Raised instead of re-measuring a schedule already quarantined."""


class UnsoundScheduleError(DeterministicScheduleError):
    """The independent soundness verifier (tenzing_tpu/verify) rejected the
    schedule: a data dependency is unordered or a cross-lane race exists.
    Deterministic by nature — the schedule is *wrong*, not unlucky — so the
    resilient layer quarantines it and it is never measured."""


class DeviceLostError(RuntimeError):
    """The device is unrecoverable; escalate (degrade or abort)."""


class FencedWriteError(RuntimeError):
    """A write was rejected by the lease epoch fence (serve/lease.py): a
    rival claim with a newer epoch exists, so this holder is a zombie —
    reclaimed during a stall on a coarse/skewed-mtime filesystem — and
    its write would be stale.  Classified transient (the item is in
    better hands, never evidence against the request), but the daemon
    treats it specially: abandon, don't retry, don't poison."""


class StoreReadonlyError(TransientError):
    """The schedule store is latched read-only (ENOSPC/EROFS/quota —
    serve/store.py ``store_readonly``): cold/near resolution would need
    a durable write that cannot land.  Transient by nature — space comes
    back, the latch clears on a successful probe — so shed-and-retry-later
    is the designed response (serve/listen.py's ``store_readonly`` shed)."""


# errno values that mean "the filesystem will not take more bytes" — not
# a flake, not worth millisecond-scale retries: latch read-only instead
_UNWRITABLE_ERRNOS = frozenset(
    getattr(_errno, name) for name in ("ENOSPC", "EDQUOT", "EROFS")
    if hasattr(_errno, name))


def is_unwritable_io(exc: BaseException) -> bool:
    """True iff ``exc`` is the full-disk family of OSError (ENOSPC /
    EDQUOT / EROFS): retrying on a backoff timescale cannot help, the
    store must degrade to read-only until a probe write succeeds."""
    return (isinstance(exc, OSError)
            and getattr(exc, "errno", None) in _UNWRITABLE_ERRNOS)


def is_transient_io(exc: BaseException) -> bool:
    """The retry predicate for hardened storage writers (THE shared
    fault/backoff.py): plain I/O flakes (EIO and friends) retry;
    the unwritable family does not (see :func:`is_unwritable_io`)."""
    return isinstance(exc, OSError) and not is_unwritable_io(exc)


# message fragments checked lowercase; order matters only across lists
# (device-lost checked first: "device lost while connection reset" is a loss)
_DEVICE_LOST_PATTERNS = (
    "device lost",
    "device_lost",
    "device or resource busy",
    "chip rebooted",
    "failed to connect to device",
    "device unreachable",
)
_TRANSIENT_PATTERNS = (
    "deadline exceeded",
    "deadline_exceeded",
    "unavailable",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "rpc error",
    "transient",
    "temporarily",
    "timed out",
    "timeout",
)
# deterministic patterns beat the generic transient words when both match
# ("RESOURCE_EXHAUSTED ... try again" is an OOM, not a flake)
_DETERMINISTIC_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "invalid_argument",
    "invalid argument",
    "unimplemented",
    "failed to compile",
    "compilation failure",
    "shape",
)


def classify_error(exc: BaseException) -> str:
    """Map an exception to a :class:`FaultClass` string (see module doc)."""
    if isinstance(exc, DeviceLostError):
        return FaultClass.DEVICE_LOST
    if isinstance(exc, FencedWriteError):
        # a zombie's rejected write is never evidence against the
        # request — the rival that fenced us is draining it right now
        return FaultClass.TRANSIENT
    if isinstance(exc, DeterministicScheduleError):
        return FaultClass.DETERMINISTIC
    if isinstance(exc, TransientError):
        return FaultClass.TRANSIENT
    msg = str(exc).lower()
    for pat in _DEVICE_LOST_PATTERNS:
        if pat in msg:
            return FaultClass.DEVICE_LOST
    for pat in _DETERMINISTIC_PATTERNS:
        if pat in msg:
            return FaultClass.DETERMINISTIC
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return FaultClass.TRANSIENT
    for pat in _TRANSIENT_PATTERNS:
        if pat in msg:
            return FaultClass.TRANSIENT
    if isinstance(exc, OSError):
        return FaultClass.TRANSIENT
    # shape/type/value errors from a broken candidate; also the default —
    # see module docstring for why unknown leans deterministic
    return FaultClass.DETERMINISTIC


def fault_code(exc: BaseException) -> int:
    """Numeric severity code of an exception's class — what the control
    plane allreduce-maxes in the rank-agreement protocol."""
    return FaultClass.CODES[classify_error(exc)]
