// Native search core: the host-side hot path of the schedule search.
//
// The reference implements its whole scheduler in C++ (graph.hpp/state.cpp/
// event_synchronizer.hpp, see SURVEY.md C2/C7/C8); here the same role is played
// by this library: the Python layer lowers an op DAG to a compact numeric
// description (ops = integer ids, kinds, edge list) and delegates the
// combinatorial work — frontier computation, sync-op inference, decision
// enumeration, equivalence-dedup'd DFS, random rollouts — to native code.
// Device execution stays in XLA; this layer never touches a device.
//
// Semantics mirror tenzing_tpu/core/{graph,event_synchronizer,state,sequence}.py
// item for item (each mirrors the reference file cited in its docstring); the
// Python test suite cross-checks the two implementations on the same graphs.

#pragma once

#include <cstdint>
#include <string>
#include <unordered_set>
#include <vector>

namespace tznative {

// Op kinds (lowered from the Python class hierarchy).
enum Kind : int32_t {
  KIND_HOST = 0,    // CpuOp/NoOp: occupies the implicit host chain
  KIND_DEVICE = 1,  // DeviceOp: must be bound to a lane before execution
  KIND_START = 2,   // Start sentinel (host semantics)
  KIND_FINISH = 3,  // Finish sentinel (host semantics)
};

// Schedule items and decisions as (tag, a, b) triples.
enum Tag : int32_t {
  TAG_EXEC = 0,        // execute op a (b = lane, -1 for host ops)
  TAG_RECORD = 1,      // EventRecord(lane=a, event=b)
  TAG_WAIT = 2,        // WaitEvent(lane=a, event=b)
  TAG_SYNC_EVENT = 3,  // EventSync(event=a)
  TAG_SYNC_LANE = 4,   // LaneSync(lane=a)
  TAG_ASSIGN = 5,      // decision only: bind op a to lane b
};

struct Item {
  int32_t tag;
  int32_t a;
  int32_t b;
  bool operator==(const Item& o) const {
    return tag == o.tag && a == o.a && b == o.b;
  }
};

// The structural DAG: ops 0..n-1 with preds/succs in edge-insertion order
// (must match the Python Graph's insertion-ordered adjacency so decision
// order is identical across implementations).
struct Graph {
  int32_t n = 0;
  std::vector<int32_t> kinds;
  std::vector<std::vector<int32_t>> preds;
  std::vector<std::vector<int32_t>> succs;
  int32_t start = -1;
  int32_t finish = -1;

  static Graph build(int32_t n_ops, const int32_t* kinds, int32_t n_edges,
                     const int32_t* edges);
};

// A partial schedule: per-op lane bindings (-1 = unbound) + the item sequence.
// The Python State carries (graph-with-bindings, sequence); bindings here are
// the graph side of that pair (graph structure itself never changes during the
// order/lane search — compound expansion happens before lowering).
struct State {
  std::vector<int32_t> bindings;
  std::vector<Item> seq;

  bool executed(int32_t op) const {
    for (const Item& it : seq)
      if (it.tag == TAG_EXEC && it.a == op) return true;
    return false;
  }
  bool is_terminal(const Graph& g) const { return executed(g.finish); }
};

// -- event synchronizer (mirrors core/event_synchronizer.py, itself the
//    reference event_synchronizer.hpp:29-242 truth table) ---------------------

// True iff every device predecessor of `op` is provably ordered before it in
// `st.seq` via record/wait (device target) or record/sync (host target) pairs.
bool is_synced(const Graph& g, const State& st, int32_t op);

// The next missing sync item(s) before `op` is executable; empty iff synced.
std::vector<Item> make_syncs(const Graph& g, const State& st, int32_t op);

// -- SDP stepping (mirrors core/state.py get_decisions/apply) -----------------

// Decisions from the frontier, in the Python layer's exact order:
// per frontier op (op-id order): Execute / Execute-sync / AssignLane-per-lane;
// deduplicated by triple equality.
std::vector<Item> get_decisions(const Graph& g, const State& st, int32_t n_lanes);

// Successor state.
State apply(const Graph& g, const State& st, const Item& decision);

// -- equivalence (mirrors core/sequence.py + core/state.py get_equivalence) ---

// Canonical form of a state under consistent lane/event renaming: the item
// sequence with lanes/events relabeled in first-use order, then (for state
// equivalence, `with_bindings`) every op's bound-ness/lane through the same
// relabeling.  Two states are bijection-equivalent iff their canonical keys
// are equal — the hashable replacement for the reference's pairwise
// Bijection checks (platform.hpp:248-270, state.cpp:126-143).
std::string canonical_key(const State& st, bool with_bindings);

// -- enumeration / rollout ----------------------------------------------------

// Worklist DFS over State::frontier with per-expansion equivalence dedup
// (mirrors solve/dfs.py get_all_sequences / reference dfs.cpp:16-82), plus
// optional terminal-sequence dedup (reference dfs.hpp:88-113).  `init_bindings`
// carries lane assignments the caller pinned in the graph (empty = all
// unbound); pinned ops are executed on their fixed lane, never re-assigned.
std::vector<State> enumerate_sequences(const Graph& g, int32_t n_lanes,
                                       int32_t max_seqs, bool dedup_terminals,
                                       const std::vector<int32_t>& init_bindings);

// Uniform-random playout to a terminal state (mirrors solve/mcts/node.py
// get_rollout's random descent).
State rollout(const Graph& g, State st, int32_t n_lanes, uint64_t seed);

}  // namespace tznative
