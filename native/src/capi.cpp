// C ABI over the native search core, consumed by tenzing_tpu/native/bridge.py
// via ctypes (the image has no pybind11; a plain C ABI also keeps the library
// usable from any host language, as the reference's C++ API is).
//
// Conventions:
//   * schedules/decisions cross the boundary as flat int32 (tag, a, b) triples
//     (see tznative::Tag);
//   * functions writing variable-length output take (out, cap) and return the
//     number of int32s written, or -needed when cap is too small (caller
//     retries), or TZ_ERROR after an exception (message via tz_last_error).

#include <cstring>
#include <string>
#include <vector>

#include "tznative/core.hpp"

using namespace tznative;

namespace {

thread_local std::string g_last_error;

constexpr int64_t TZ_ERROR = -1000000000;

State make_state(const Graph& g, const int32_t* bindings, int32_t seq_len,
                 const int32_t* seq) {
  State st;
  st.bindings.assign(bindings, bindings + g.n);
  st.seq.reserve(seq_len);
  for (int32_t i = 0; i < seq_len; ++i)
    st.seq.push_back({seq[3 * i], seq[3 * i + 1], seq[3 * i + 2]});
  return st;
}

int64_t write_items(const std::vector<Item>& items, int32_t* out, int64_t cap) {
  int64_t need = (int64_t)items.size() * 3;
  if (need > cap) return -need;
  for (size_t i = 0; i < items.size(); ++i) {
    out[3 * i] = items[i].tag;
    out[3 * i + 1] = items[i].a;
    out[3 * i + 2] = items[i].b;
  }
  return need;
}

}  // namespace

extern "C" {

int32_t tz_abi_version() { return 2; }

const char* tz_last_error() { return g_last_error.c_str(); }

void* tz_graph_create(int32_t n_ops, const int32_t* kinds, int32_t n_edges,
                      const int32_t* edges) {
  try {
    return new Graph(Graph::build(n_ops, kinds, n_edges, edges));
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

void tz_graph_destroy(void* g) { delete static_cast<Graph*>(g); }

// Decisions of a state, as triples.  Returns #int32s written / -needed / TZ_ERROR.
int64_t tz_decisions(void* gp, int32_t n_lanes, const int32_t* bindings,
                     int32_t seq_len, const int32_t* seq, int32_t* out,
                     int64_t cap) {
  try {
    const Graph& g = *static_cast<Graph*>(gp);
    State st = make_state(g, bindings, seq_len, seq);
    return write_items(get_decisions(g, st, n_lanes), out, cap);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return TZ_ERROR;
  }
}

// Random playout to terminal.  Writes the FULL final sequence (prefix
// included) to out_seq; lane assignments ride in the TAG_EXEC items.
int64_t tz_rollout(void* gp, int32_t n_lanes, const int32_t* bindings,
                   int32_t seq_len, const int32_t* seq, uint64_t seed,
                   int32_t* out_seq, int64_t cap) {
  try {
    const Graph& g = *static_cast<Graph*>(gp);
    State st = rollout(g, make_state(g, bindings, seq_len, seq), n_lanes, seed);
    return write_items(st.seq, out_seq, cap);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return TZ_ERROR;
  }
}

namespace {
// Result of the last tz_enum_run on this thread, fetched by tz_enum_fetch —
// a two-phase protocol so an undersized fetch buffer never re-runs the
// (potentially exponential) enumeration.
thread_local std::vector<int32_t> g_enum_result;
}  // namespace

// Exhaustive dedup'd enumeration (phase 1: compute).  `bindings` carries
// caller-pinned lane assignments (or all -1).  Stores the result thread-local;
// returns total int32s to fetch / TZ_ERROR; *n_seqs_out = #sequences.
// Layout per sequence: [n_items, tag,a,b, tag,a,b, ...].
int64_t tz_enum_run(void* gp, int32_t n_lanes, const int32_t* bindings,
                    int32_t max_seqs, int32_t dedup_terminals,
                    int32_t* n_seqs_out) {
  try {
    const Graph& g = *static_cast<Graph*>(gp);
    std::vector<int32_t> init(bindings, bindings + g.n);
    std::vector<State> terminals =
        enumerate_sequences(g, n_lanes, max_seqs, dedup_terminals != 0, init);
    *n_seqs_out = (int32_t)terminals.size();
    g_enum_result.clear();
    for (const State& st : terminals) {
      g_enum_result.push_back((int32_t)st.seq.size());
      for (const Item& it : st.seq) {
        g_enum_result.push_back(it.tag);
        g_enum_result.push_back(it.a);
        g_enum_result.push_back(it.b);
      }
    }
    return (int64_t)g_enum_result.size();
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return TZ_ERROR;
  }
}

// Phase 2: copy the stored result out and release it.  Returns int32s written
// or -needed (result retained so the caller can retry with a bigger buffer).
int64_t tz_enum_fetch(int32_t* out, int64_t cap) {
  int64_t need = (int64_t)g_enum_result.size();
  if (need > cap) return -need;
  std::memcpy(out, g_enum_result.data(), need * sizeof(int32_t));
  g_enum_result.clear();
  g_enum_result.shrink_to_fit();
  return need;
}

}  // extern "C"
