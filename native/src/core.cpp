// Implementation of the native search core.  Every function cites the Python
// module it mirrors; the Python docstrings carry the reference (CUDA/C++)
// file:line provenance.

#include "tznative/core.hpp"

#include <algorithm>
#include <random>
#include <stdexcept>

namespace tznative {

Graph Graph::build(int32_t n_ops, const int32_t* kinds_in, int32_t n_edges,
                   const int32_t* edges) {
  Graph g;
  g.n = n_ops;
  g.kinds.assign(kinds_in, kinds_in + n_ops);
  g.preds.resize(n_ops);
  g.succs.resize(n_ops);
  for (int32_t i = 0; i < n_ops; ++i) {
    if (g.kinds[i] == KIND_START) g.start = i;
    if (g.kinds[i] == KIND_FINISH) g.finish = i;
  }
  for (int32_t e = 0; e < n_edges; ++e) {
    int32_t a = edges[2 * e], b = edges[2 * e + 1];
    if (a < 0 || a >= n_ops || b < 0 || b >= n_ops)
      throw std::invalid_argument("edge endpoint out of range");
    // duplicate-edge tolerance matches Python Graph.then (graph.py:63-72)
    if (std::find(g.succs[a].begin(), g.succs[a].end(), b) == g.succs[a].end())
      g.succs[a].push_back(b);
    if (std::find(g.preds[b].begin(), g.preds[b].end(), a) == g.preds[b].end())
      g.preds[b].push_back(a);
  }
  if (g.start < 0 || g.finish < 0)
    throw std::invalid_argument("graph must contain start and finish sentinels");
  return g;
}

// -- synchronizer -------------------------------------------------------------

namespace {

// one-pass exec-position table (op id -> seq index, -1 = not executed); built
// once per get_decisions/is_synced/make_syncs call so the per-predecessor
// lookups are O(1) instead of O(|seq|)
std::vector<int> exec_index(const Graph& g, const State& st) {
  std::vector<int> idx(g.n, -1);
  for (size_t i = 0; i < st.seq.size(); ++i)
    if (st.seq[i].tag == TAG_EXEC) idx[st.seq[i].a] = (int)i;
  return idx;
}

// mirrors event_synchronizer.py _device_then_device_synced
bool device_then_device_synced(const State& st, int32_t pred_lane, int pred_idx,
                               int32_t op_lane) {
  if (pred_lane == op_lane) return true;
  const auto& s = st.seq;
  for (size_t i = pred_idx + 1; i < s.size(); ++i) {
    if (s[i].tag == TAG_RECORD && s[i].a == pred_lane) {
      for (size_t j = i + 1; j < s.size(); ++j)
        if (s[j].tag == TAG_WAIT && s[j].a == op_lane && s[j].b == s[i].b)
          return true;
    }
  }
  return false;
}

// mirrors event_synchronizer.py _device_then_host_synced
bool device_then_host_synced(const State& st, int32_t pred_lane, int pred_idx) {
  const auto& s = st.seq;
  for (size_t i = pred_idx + 1; i < s.size(); ++i) {
    if (s[i].tag == TAG_SYNC_LANE && s[i].a == pred_lane) return true;
    if (s[i].tag == TAG_RECORD && s[i].a == pred_lane) {
      for (size_t j = i + 1; j < s.size(); ++j)
        if (s[j].tag == TAG_SYNC_EVENT && s[j].a == s[i].b) return true;
    }
  }
  return false;
}

// first EventRecord on `lane` after `pos` (event_synchronizer.py _find_record_after)
int find_record_after(const State& st, int pos, int32_t lane) {
  for (size_t i = pos + 1; i < st.seq.size(); ++i)
    if (st.seq[i].tag == TAG_RECORD && st.seq[i].a == lane) return (int)i;
  return -1;
}

// smallest event id unused in seq and pending syncs (sequence.py new_unique_event)
int32_t fresh_event(const State& st, const std::vector<Item>& pending) {
  std::unordered_set<int32_t> used;
  auto note = [&used](const Item& it) {
    if (it.tag == TAG_RECORD || it.tag == TAG_WAIT) used.insert(it.b);
    if (it.tag == TAG_SYNC_EVENT) used.insert(it.a);
  };
  for (const Item& it : st.seq) note(it);
  for (const Item& it : pending) note(it);
  int32_t e = 0;
  while (used.count(e)) ++e;
  return e;
}

bool is_bound_device(const Graph& g, const State& st, int32_t op) {
  return g.kinds[op] == KIND_DEVICE && st.bindings[op] >= 0;
}

}  // namespace

bool is_synced_impl(const Graph& g, const State& st, int32_t op,
                    const std::vector<int>& eidx) {
  bool op_device = is_bound_device(g, st, op);
  int32_t op_lane = op_device ? st.bindings[op] : -1;
  for (int32_t pred : g.preds[op]) {
    if (!is_bound_device(g, st, pred)) continue;  // host -> anything is free
    int pi = eidx[pred];
    if (pi < 0) throw std::logic_error("is_synced: predecessor not executed");
    if (op_device) {
      if (!device_then_device_synced(st, st.bindings[pred], pi, op_lane))
        return false;
    } else {
      if (!device_then_host_synced(st, st.bindings[pred], pi)) return false;
    }
  }
  return true;
}

std::vector<Item> make_syncs_impl(const Graph& g, const State& st, int32_t op,
                                  const std::vector<int>& eidx) {
  std::vector<Item> syncs;
  auto emit = [&syncs](const Item& s) {
    if (std::find(syncs.begin(), syncs.end(), s) == syncs.end())
      syncs.push_back(s);
  };
  bool op_device = is_bound_device(g, st, op);
  int32_t op_lane = op_device ? st.bindings[op] : -1;
  for (int32_t pred : g.preds[op]) {
    if (!is_bound_device(g, st, pred)) continue;
    int32_t pred_lane = st.bindings[pred];
    int pi = eidx[pred];
    if (pi < 0) throw std::logic_error("make_syncs: predecessor not executed");
    if (op_device) {
      if (device_then_device_synced(st, pred_lane, pi, op_lane)) continue;
    } else {
      if (device_then_host_synced(st, pred_lane, pi)) continue;
    }
    int ri = find_record_after(st, pi, pred_lane);
    if (ri < 0) {
      // covered if an identical-lane record is already pending this call
      bool pending = false;
      for (const Item& s : syncs)
        if (s.tag == TAG_RECORD && s.a == pred_lane) pending = true;
      if (!pending)
        emit({TAG_RECORD, pred_lane, fresh_event(st, syncs)});
    } else if (op_device) {
      emit({TAG_WAIT, op_lane, st.seq[ri].b});
    } else {
      emit({TAG_SYNC_EVENT, st.seq[ri].b, -1});
    }
  }
  return syncs;
}

bool is_synced(const Graph& g, const State& st, int32_t op) {
  return is_synced_impl(g, st, op, exec_index(g, st));
}

std::vector<Item> make_syncs(const Graph& g, const State& st, int32_t op) {
  return make_syncs_impl(g, st, op, exec_index(g, st));
}

// -- SDP stepping -------------------------------------------------------------

std::vector<Item> get_decisions(const Graph& g, const State& st, int32_t n_lanes) {
  // frontier: ops not executed whose preds are all executed, in op-id order
  // (mirrors graph.py frontier over insertion-ordered vertices)
  std::vector<int> eidx = exec_index(g, st);
  std::vector<bool> done(g.n, false);
  for (int32_t v = 0; v < g.n; ++v) done[v] = eidx[v] >= 0;
  std::vector<Item> decisions;
  auto emit = [&decisions](const Item& d) {
    if (std::find(decisions.begin(), decisions.end(), d) == decisions.end())
      decisions.push_back(d);
  };
  for (int32_t v = 0; v < g.n; ++v) {
    if (done[v]) continue;
    bool ready = true;
    for (int32_t p : g.preds[v])
      if (!done[p]) { ready = false; break; }
    if (!ready) continue;
    if (g.kinds[v] == KIND_DEVICE && st.bindings[v] < 0) {
      for (int32_t l = 0; l < n_lanes; ++l) emit({TAG_ASSIGN, v, l});
      continue;
    }
    std::vector<Item> syncs = make_syncs_impl(g, st, v, eidx);
    if (syncs.empty()) {
      emit({TAG_EXEC, v, g.kinds[v] == KIND_DEVICE ? st.bindings[v] : -1});
    } else {
      for (const Item& s : syncs) emit(s);
    }
  }
  return decisions;
}

State apply(const Graph& g, const State& st, const Item& d) {
  State nx = st;
  if (d.tag == TAG_ASSIGN) {
    nx.bindings[d.a] = d.b;
  } else if (d.tag == TAG_EXEC) {
    nx.seq.push_back({TAG_EXEC, d.a, g.kinds[d.a] == KIND_DEVICE ? st.bindings[d.a] : -1});
  } else {
    nx.seq.push_back(d);  // a sync item is executed by appending it
  }
  return nx;
}

// -- equivalence --------------------------------------------------------------

namespace {

struct Relabel {
  std::vector<int32_t> map;  // id -> label, -1 = unseen
  int32_t next = 0;
  int32_t operator()(int32_t id) {
    if (id < 0) return id;
    if ((size_t)id >= map.size()) map.resize(id + 1, -1);
    if (map[id] < 0) map[id] = next++;
    return map[id];
  }
};

}  // namespace

std::string canonical_key(const State& st, bool with_bindings) {
  Relabel lane, event;
  std::vector<int32_t> key;
  key.reserve(st.seq.size() * 3 + (with_bindings ? st.bindings.size() : 0));
  for (const Item& it : st.seq) {
    key.push_back(it.tag);
    switch (it.tag) {
      case TAG_EXEC:
        key.push_back(it.a);
        key.push_back(lane(it.b));
        break;
      case TAG_RECORD:
      case TAG_WAIT:
        key.push_back(lane(it.a));
        key.push_back(event(it.b));
        break;
      case TAG_SYNC_EVENT:
        key.push_back(event(it.a));
        key.push_back(-1);
        break;
      case TAG_SYNC_LANE:
        key.push_back(lane(it.a));
        key.push_back(-1);
        break;
      default:
        throw std::logic_error("canonical_key: unexpected tag");
    }
  }
  if (with_bindings) {
    // the graph half of state equivalence (state.py get_equivalence): every
    // vertex's bound-ness and lane through the same renaming
    key.push_back(-2);  // section separator
    for (int32_t b : st.bindings) key.push_back(lane(b));
  }
  return std::string(reinterpret_cast<const char*>(key.data()),
                     key.size() * sizeof(int32_t));
}

// -- enumeration / rollout ----------------------------------------------------

std::vector<State> enumerate_sequences(const Graph& g, int32_t n_lanes,
                                       int32_t max_seqs, bool dedup_terminals,
                                       const std::vector<int32_t>& init_bindings) {
  std::vector<State> terminals;
  std::vector<State> stack;
  State init;
  if (init_bindings.empty()) {
    init.bindings.assign(g.n, -1);
  } else {
    if ((int32_t)init_bindings.size() != g.n)
      throw std::invalid_argument("init_bindings size mismatch");
    init.bindings = init_bindings;
  }
  init.seq.push_back({TAG_EXEC, g.start, -1});
  stack.push_back(std::move(init));
  std::unordered_set<std::string> terminal_keys;
  while (!stack.empty() && (int32_t)terminals.size() < max_seqs) {
    State st = std::move(stack.back());
    stack.pop_back();
    if (st.is_terminal(g)) {
      if (dedup_terminals) {
        // terminal dedup is sequence-only (solve/dfs.py _dedup_terminal_states)
        std::string k = canonical_key(st, /*with_bindings=*/false);
        if (!terminal_keys.insert(std::move(k)).second) continue;
      }
      terminals.push_back(std::move(st));
      continue;
    }
    // per-expansion successor dedup under full state equivalence
    // (state.py State.frontier with dedup=True)
    std::unordered_set<std::string> succ_keys;
    for (const Item& d : get_decisions(g, st, n_lanes)) {
      State nx = apply(g, st, d);
      std::string k = canonical_key(nx, /*with_bindings=*/true);
      if (succ_keys.insert(std::move(k)).second) stack.push_back(std::move(nx));
    }
  }
  return terminals;
}

State rollout(const Graph& g, State st, int32_t n_lanes, uint64_t seed) {
  std::mt19937_64 rng(seed);
  while (!st.is_terminal(g)) {
    std::vector<Item> ds = get_decisions(g, st, n_lanes);
    if (ds.empty())
      throw std::logic_error("rollout: non-terminal state with no decisions");
    std::uniform_int_distribution<size_t> pick(0, ds.size() - 1);
    st = apply(g, st, ds[pick(rng)]);
  }
  return st;
}

}  // namespace tznative
